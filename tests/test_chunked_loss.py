"""Chunked preference/distill losses (``ops/chunked_loss.py``) — parity
vs naive fp32 references that DO materialize the (T, V) logits tensor,
plus the memory contract the op exists for: AOT-compiled grads never
allocate a full-logits buffer (asserted on the lowered HLO and on
``memory_analysis``), while the naive formulation provably does.
Reference capability lineage: Liger Kernel's chunked fused-linear losses
(arXiv 2410.10989), rebuilt on ``linear_xent``'s online-softmax stats."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.ops import _common
from apex1_tpu.ops.chunked_loss import (
    check_chunk_geometry, chunked_dpo_loss, chunked_kl_loss,
    chunked_logprob, chunked_orpo_loss)

FP32_TOL = dict(rtol=2e-5, atol=2e-5)
_NEG = -1e30


# ---------------------------------------------------------------------------
# Naive references — materialized logits, fp32 throughout
# ---------------------------------------------------------------------------


def _naive_logprob(x, w, targets, num_classes=None):
    logits = jnp.einsum("...h,vh->...v", x.astype(jnp.float32),
                        w.astype(jnp.float32))
    if num_classes is not None:
        valid = jnp.arange(w.shape[0]) < num_classes
        logits = jnp.where(valid, logits, _NEG)
    lp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(
        lp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]


def _naive_seq_logp(h, w, t, padding_idx=None, num_classes=None):
    lp = _naive_logprob(h, w, t, num_classes)
    mask = (jnp.ones(t.shape, jnp.float32) if padding_idx is None
            else (t != padding_idx).astype(jnp.float32))
    return jnp.sum(lp * mask, axis=-1), jnp.sum(mask, axis=-1)


def _naive_dpo(hc, hr, w, tc, tr, rc, rr, beta=0.1, padding_idx=None):
    sc, _ = _naive_seq_logp(hc, w, tc, padding_idx)
    sr, _ = _naive_seq_logp(hr, w, tr, padding_idx)
    return -jnp.mean(jax.nn.log_sigmoid(beta * ((sc - sr) - (rc - rr))))


def _naive_orpo(hc, hr, w, tc, tr, lam=0.1, padding_idx=None):
    sc, lc = _naive_seq_logp(hc, w, tc, padding_idx)
    sr, lr = _naive_seq_logp(hr, w, tr, padding_idx)
    lc, lr = jnp.maximum(lc, 1.0), jnp.maximum(lr, 1.0)

    def odds(avg):
        p = jnp.clip(jnp.exp(avg), None, 1.0 - 1e-6)
        return avg - jnp.log1p(-p)

    ratio = odds(sc / lc) - odds(sr / lr)
    return (jnp.mean(-sc / lc)
            + lam * jnp.mean(-jax.nn.log_sigmoid(ratio)))


def _naive_kl(xs, ws, xt, wt, temperature=1.0, num_classes=None):
    ls = jnp.einsum("...h,vh->...v", xs.astype(jnp.float32),
                    ws.astype(jnp.float32)) / temperature
    lt = jnp.einsum("...h,vh->...v", xt.astype(jnp.float32),
                    wt.astype(jnp.float32)) / temperature
    if num_classes is not None:
        valid = jnp.arange(ws.shape[0]) < num_classes
        ls = jnp.where(valid, ls, _NEG)
        lt = jnp.where(valid, lt, _NEG)
    pt = jax.nn.softmax(lt, axis=-1)
    return jnp.sum(pt * (jax.nn.log_softmax(lt, axis=-1)
                         - jax.nn.log_softmax(ls, axis=-1)), axis=-1)


def _mk(rng, *shape):
    return jnp.asarray(rng.normal(size=shape) * 0.3, jnp.float32)


# ---------------------------------------------------------------------------
# chunked_logprob
# ---------------------------------------------------------------------------


class TestChunkedLogprob:
    @pytest.mark.parametrize("chunk_v", [128, 256])
    def test_parity_and_grads(self, rng, chunk_v):
        B, S, H, V = 2, 12, 64, 517  # ragged V exercises tail masking
        x = _mk(rng, B, S, H)
        w = _mk(rng, V, H)
        t = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)

        lp = chunked_logprob(x, w, t, chunk_v=chunk_v)
        ref = _naive_logprob(x, w, t)
        assert lp.shape == (B, S) and lp.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(lp), np.asarray(ref),
                                   **FP32_TOL)

        gp = jax.grad(lambda x, w: jnp.sum(
            chunked_logprob(x, w, t, chunk_v=chunk_v)), argnums=(0, 1))(
            x, w)
        gg = jax.grad(lambda x, w: jnp.sum(
            _naive_logprob(x, w, t)), argnums=(0, 1))(x, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_chunk_size_invariance(self, rng):
        T, H, V = 16, 32, 512
        x, w = _mk(rng, T, H), _mk(rng, V, H)
        t = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)
        base = chunked_logprob(x, w, t, chunk_v=512)
        for cv in (128, 256):
            np.testing.assert_allclose(
                np.asarray(chunked_logprob(x, w, t, chunk_v=cv)),
                np.asarray(base), rtol=1e-6, atol=1e-6)

    def test_num_classes_masks_pad_vocab(self, rng):
        T, H, V, k = 8, 32, 384, 300
        x, w = _mk(rng, T, H), _mk(rng, V, H)
        t = jnp.asarray(rng.integers(0, k, size=(T,)), jnp.int32)
        lp = chunked_logprob(x, w, t, chunk_v=128, num_classes=k)
        ref = _naive_logprob(x, w, t, num_classes=k)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(ref),
                                   **FP32_TOL)

    def test_pallas_path_matches_xla_path(self, rng):
        T, H, V = 16, 64, 512
        x, w = _mk(rng, T, H), _mk(rng, V, H)
        t = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)
        def loss(x, w, impl):
            with _common.force_impl(impl):
                return chunked_logprob(x, w, t, chunk_v=256,
                                       block_t=8, block_v=128)

        np.testing.assert_allclose(
            np.asarray(loss(x, w, "pallas")),
            np.asarray(loss(x, w, "xla")), **FP32_TOL)
        gp = jax.grad(lambda x, w: jnp.sum(loss(x, w, "pallas")),
                      argnums=(0, 1))(x, w)
        gg = jax.grad(lambda x, w: jnp.sum(loss(x, w, "xla")),
                      argnums=(0, 1))(x, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_geometry_negatives_raise(self):
        with pytest.raises(ValueError, match="multiple"):
            check_chunk_geometry(100, 64)
        with pytest.raises(ValueError, match="VMEM"):
            check_chunk_geometry(1 << 24, 8192)
        # and through the public entry point
        with pytest.raises(ValueError, match="multiple"):
            chunked_logprob(jnp.zeros((4, 32)), jnp.zeros((256, 32)),
                            jnp.zeros((4,), jnp.int32), chunk_v=100)


# ---------------------------------------------------------------------------
# DPO / ORPO
# ---------------------------------------------------------------------------


class TestPreferenceLosses:
    @pytest.mark.parametrize("padding_idx", [None, 0])
    def test_dpo_parity_and_grads(self, rng, padding_idx):
        B, S, H, V = 3, 10, 48, 389
        hc, hr = _mk(rng, B, S, H), _mk(rng, B, S, H)
        w = _mk(rng, V, H)
        tc = np.asarray(rng.integers(1, V, size=(B, S)), np.int32)
        tr = np.asarray(rng.integers(1, V, size=(B, S)), np.int32)
        if padding_idx is not None:
            tc[:, -3:] = padding_idx
            tr[:, -2:] = padding_idx
        tc, tr = jnp.asarray(tc), jnp.asarray(tr)
        rc = jnp.asarray(rng.normal(size=(B,)) * 2.0, jnp.float32)
        rr = jnp.asarray(rng.normal(size=(B,)) * 2.0, jnp.float32)

        def fused(hc, hr, w):
            return chunked_dpo_loss(hc, hr, w, tc, tr, rc, rr, beta=0.25,
                                    padding_idx=padding_idx, chunk_v=128)

        def gold(hc, hr, w):
            return _naive_dpo(hc, hr, w, tc, tr, rc, rr, beta=0.25,
                              padding_idx=padding_idx)

        np.testing.assert_allclose(np.asarray(fused(hc, hr, w)),
                                   np.asarray(gold(hc, hr, w)), **FP32_TOL)
        gp = jax.grad(fused, argnums=(0, 1, 2))(hc, hr, w)
        gg = jax.grad(gold, argnums=(0, 1, 2))(hc, hr, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_orpo_parity_and_grads(self, rng):
        B, S, H, V = 2, 8, 32, 261
        hc, hr = _mk(rng, B, S, H), _mk(rng, B, S, H)
        w = _mk(rng, V, H)
        tc = jnp.asarray(rng.integers(1, V, size=(B, S)), jnp.int32)
        tr = jnp.asarray(rng.integers(1, V, size=(B, S)), jnp.int32)

        def fused(hc, hr, w):
            return chunked_orpo_loss(hc, hr, w, tc, tr, lam=0.3,
                                     chunk_v=128)

        def gold(hc, hr, w):
            return _naive_orpo(hc, hr, w, tc, tr, lam=0.3)

        np.testing.assert_allclose(np.asarray(fused(hc, hr, w)),
                                   np.asarray(gold(hc, hr, w)), **FP32_TOL)
        gp = jax.grad(fused, argnums=(0, 1, 2))(hc, hr, w)
        gg = jax.grad(gold, argnums=(0, 1, 2))(hc, hr, w)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)


# ---------------------------------------------------------------------------
# KL distillation
# ---------------------------------------------------------------------------


class TestChunkedKL:
    @pytest.mark.parametrize("temperature", [1.0, 2.5])
    def test_parity_and_student_grads(self, rng, temperature):
        B, S, H, V = 2, 6, 40, 453
        xs, xt = _mk(rng, B, S, H), _mk(rng, B, S, H)
        ws, wt = _mk(rng, V, H), _mk(rng, V, H)

        kl = chunked_kl_loss(xs, ws, xt, wt, temperature=temperature,
                             chunk_v=128)
        ref = _naive_kl(xs, ws, xt, wt, temperature=temperature)
        assert kl.shape == (B, S) and kl.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(kl), np.asarray(ref),
                                   **FP32_TOL)

        gp = jax.grad(lambda xs, ws: jnp.sum(chunked_kl_loss(
            xs, ws, xt, wt, temperature=temperature, chunk_v=128)),
            argnums=(0, 1))(xs, ws)
        gg = jax.grad(lambda xs, ws: jnp.sum(_naive_kl(
            xs, ws, xt, wt, temperature=temperature)),
            argnums=(0, 1))(xs, ws)
        for a, b in zip(gp, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       **FP32_TOL)

    def test_teacher_is_stop_grad(self, rng):
        T, H, V = 8, 32, 256
        xs, xt = _mk(rng, T, H), _mk(rng, T, H)
        ws, wt = _mk(rng, V, H), _mk(rng, V, H)
        gt = jax.grad(lambda xt, wt: jnp.sum(chunked_kl_loss(
            xs, ws, xt, wt, chunk_v=128)), argnums=(0, 1))(xt, wt)
        for g in gt:
            assert not np.any(np.asarray(g))

    def test_vocab_mismatch_raises(self):
        with pytest.raises(ValueError, match="one vocab"):
            chunked_kl_loss(jnp.zeros((4, 32)), jnp.zeros((256, 32)),
                            jnp.zeros((4, 32)), jnp.zeros((384, 32)),
                            chunk_v=128)


# ---------------------------------------------------------------------------
# The memory contract — AOT proof that logits are never materialized
# ---------------------------------------------------------------------------

_B, _S, _H, _V, _CV = 4, 64, 32, 4096, 256
_BT = _B * _S  # 256 tokens → full logits = 1,048,576 f32 elements


def _f32_buffer_elems(hlo_text):
    """Element counts of every f32 buffer shape in the lowered HLO."""
    out = []
    for dims in re.findall(r"f32\[([0-9,]+)\]", hlo_text):
        n = 1
        for d in dims.split(","):
            n *= int(d)
        out.append(n)
    return out


def _compile_grad(loss_fn, *args):
    return jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2))).lower(
        *args).compile()


class TestNoLogitsMaterialization:
    """The acceptance criterion: AOT analysis proves chunked DPO's
    compiled grad never allocates a [B·S, V]-sized fp32 buffer, while
    the naive formulation (positive control) provably does.  Runs on
    the CPU backend — buffer shapes in lowered HLO are backend-agnostic
    facts about the program."""

    def _inputs(self, rng):
        hc = _mk(rng, _B, _S, _H)
        hr = _mk(rng, _B, _S, _H)
        w = _mk(rng, _V, _H)
        tc = jnp.asarray(rng.integers(0, _V, size=(_B, _S)), jnp.int32)
        tr = jnp.asarray(rng.integers(0, _V, size=(_B, _S)), jnp.int32)
        rc = jnp.zeros((_B,), jnp.float32)
        rr = jnp.zeros((_B,), jnp.float32)
        return hc, hr, w, tc, tr, rc, rr

    def test_chunked_dpo_never_materializes_logits(self, rng):
        hc, hr, w, tc, tr, rc, rr = self._inputs(rng)

        def loss(hc, hr, w):
            return chunked_dpo_loss(hc, hr, w, tc, tr, rc, rr,
                                    chunk_v=_CV)

        compiled = _compile_grad(loss, hc, hr, w)
        big = [n for n in _f32_buffer_elems(compiled.as_text())
               if n >= _BT * _V]
        assert not big, (
            f"chunked DPO grad allocates full-logits-sized f32 buffers: "
            f"{big} (≥ {_BT * _V} elements)")
        mem = compiled.memory_analysis()
        if mem is not None and mem.temp_size_in_bytes:
            assert mem.temp_size_in_bytes < _BT * _V * 4, (
                f"temp {mem.temp_size_in_bytes} B ≥ one full logits "
                f"tensor ({_BT * _V * 4} B)")

    def test_naive_dpo_does_materialize(self, rng):
        """Positive control: the same geometry through materialized
        logits shows a ≥ [B·S, V] f32 buffer — proving the scan above
        actually detects what it claims to rule out."""
        hc, hr, w, tc, tr, rc, rr = self._inputs(rng)

        def loss(hc, hr, w):
            return _naive_dpo(hc, hr, w, tc, tr, rc, rr)

        compiled = _compile_grad(loss, hc, hr, w)
        big = [n for n in _f32_buffer_elems(compiled.as_text())
               if n >= _BT * _V]
        assert big, "positive control failed: no full-logits buffer found"

    def test_chunked_logprob_grad_never_materializes(self, rng):
        x = _mk(rng, _BT, _H)
        w = _mk(rng, _V, _H)
        t = jnp.asarray(rng.integers(0, _V, size=(_BT,)), jnp.int32)

        def loss(x, w, _unused):
            return jnp.sum(chunked_logprob(x, w, t, chunk_v=_CV))

        compiled = _compile_grad(loss, x, w, jnp.zeros(()))
        big = [n for n in _f32_buffer_elems(compiled.as_text())
               if n >= _BT * _V]
        assert not big, f"full-logits-sized buffers: {big}"

    def test_chunked_kl_grad_never_materializes(self, rng):
        xs = _mk(rng, _BT, _H)
        xt = _mk(rng, _BT, _H)
        ws = _mk(rng, _V, _H)
        wt = _mk(rng, _V, _H)

        def loss(xs, ws, _unused):
            return jnp.sum(chunked_kl_loss(xs, ws, xt, wt, chunk_v=_CV))

        compiled = _compile_grad(loss, xs, ws, jnp.zeros(()))
        big = [n for n in _f32_buffer_elems(compiled.as_text())
               if n >= _BT * _V]
        assert not big, f"full-logits-sized buffers: {big}"
