"""T5 encoder-decoder tests — model correctness properties (causal /
pad-mask invariance, fused-head CE vs materialized-logits gold,
Pallas-vs-XLA whole-model parity) plus the pipelined enc-dec composition:
encoder and decoder stages share one pad-to-max pipeline boundary (the
SURVEY #56 ``decoder_seq_length`` scenario) and must reproduce the flat
model's loss and grads exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.t5 import (RelPosBias, T5, T5Block, T5Config,
                                 relative_position_bucket, t5_loss_fn)
from apex1_tpu.transformer.pipeline_parallel import schedules


@pytest.fixture(scope="module")
def tiny():
    cfg = T5Config.tiny(policy=get_policy("O0"))
    model = T5(cfg)
    rng = np.random.default_rng(7)
    enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32)
    params = model.init(jax.random.key(0), enc, dec)["params"]
    return cfg, model, params, enc, dec


class TestRelPosBucket:
    def test_range_and_zero(self):
        rel = jnp.arange(-300, 300)
        for bidir in (True, False):
            b = relative_position_bucket(rel, bidirectional=bidir,
                                         num_buckets=32, max_distance=128)
            assert int(b.min()) >= 0 and int(b.max()) < 32
        assert int(relative_position_bucket(
            jnp.asarray(0), bidirectional=True)) == 0

    def test_unidirectional_future_is_bucket_zero(self):
        """Decoder buckets: memory positions AFTER the query all land in
        bucket 0 (they're masked anyway; T5 semantics)."""
        b = relative_position_bucket(jnp.arange(1, 50),
                                     bidirectional=False)
        assert int(jnp.max(b)) == 0

    def test_bidirectional_splits_past_future(self):
        past = relative_position_bucket(jnp.asarray(-3),
                                        bidirectional=True, num_buckets=32)
        future = relative_position_bucket(jnp.asarray(3),
                                          bidirectional=True,
                                          num_buckets=32)
        assert int(future) >= 16 and int(past) < 16

    def test_config_rejects_degenerate_log_range(self):
        """ADVICE r3: max_dist <= buckets//2 makes the log-bucket
        denominator zero/negative, silently wrapping garbage indices into
        the bias table — the config must fail fast instead."""
        with pytest.raises(ValueError, match="rel_pos_max_dist"):
            T5Config.tiny(rel_pos_buckets=8, rel_pos_max_dist=4)
        with pytest.raises(ValueError, match="rel_pos_max_dist"):
            T5Config.tiny(rel_pos_buckets=8, rel_pos_max_dist=2)
        T5Config.tiny(rel_pos_buckets=8, rel_pos_max_dist=5)  # ok

    def test_log_spacing_saturates(self):
        b1 = relative_position_bucket(jnp.asarray(-127),
                                      bidirectional=False,
                                      num_buckets=32, max_distance=128)
        b2 = relative_position_bucket(jnp.asarray(-4000),
                                      bidirectional=False,
                                      num_buckets=32, max_distance=128)
        assert int(b2) == 31 and int(b1) <= 31


class TestT5Model:
    @pytest.mark.slow  # ~28s whole-model value_and_grad compile; the
    # COMPOSITION check. Halves pinned tier-1: the fused CE kernel's
    # numerics/grads in test_linear_xent.py +
    # test_vocab_parallel_linear_xent.py, and T5's behavioral pins
    # (causal/pad/label-pad invariance) below. Runs via check_all --all.
    def test_fused_head_matches_gold_and_grads_alive(self, tiny):
        """One value_and_grad trace covers both the fused-vs-gold CE check
        and the no-dead-params check (compile time dominates on CPU)."""
        cfg, model, params, enc, dec = tiny
        fused, grads = jax.value_and_grad(t5_loss_fn(model))(params, enc,
                                                            dec)
        gold = t5_loss_fn(model, fuse_head=False)(params, enc, dec)
        np.testing.assert_allclose(float(fused), float(gold), rtol=1e-5)
        dead = [jax.tree_util.keystr(p)
                for p, g in jax.tree_util.tree_leaves_with_path(grads)
                if float(jnp.max(jnp.abs(g))) == 0.0]
        assert not dead, f"dead-grad params: {dead}"

    def test_decoder_causal_invariance(self, tiny):
        """Changing future decoder tokens must not move earlier logits."""
        cfg, model, params, enc, dec = tiny
        la = model.apply({"params": params}, enc, dec)[:, :5]
        lb = model.apply({"params": params}, enc,
                         dec.at[:, 5:].set(3))[:, :5]
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_encoder_pad_mask_invariance(self, tiny):
        """Tokens under a pad mask must not affect any logit."""
        cfg, model, params, enc, dec = tiny
        mask = jnp.asarray([[True] * 8 + [False] * 4, [True] * 12])
        la = model.apply({"params": params}, enc, dec, enc_pad_mask=mask)
        lb = model.apply({"params": params}, enc.at[0, 8:].set(5), dec,
                         enc_pad_mask=mask)
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_label_pad_excluded(self, tiny):
        cfg, model, params, enc, dec = tiny
        # padding the last two label positions must change the loss to the
        # mean over the kept positions only — checked against a
        # hand-computed masked mean from the raw logits
        dec_p = dec.at[:, -2:].set(0)
        lf = t5_loss_fn(model, label_pad_id=0)
        l_masked = float(lf(params, enc, dec_p))
        logits = np.asarray(
            model.apply({"params": params}, enc, dec_p[:, :-1]),
            np.float64)
        labels = np.asarray(dec_p[:, 1:])
        lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                     .sum(-1)) + logits.max(-1)
        nll = lse - np.take_along_axis(logits, labels[..., None],
                                       -1)[..., 0]
        keep = labels != 0
        assert keep.sum() < labels.size, "test needs real pad positions"
        np.testing.assert_allclose(l_masked, nll[keep].mean(), rtol=1e-5)
        # with no pad ids present, label_pad_id loss == plain mean loss
        dec_np = jnp.where(dec == 0, 1, dec)
        np.testing.assert_allclose(
            float(lf(params, enc, dec_np)),
            float(t5_loss_fn(model)(params, enc, dec_np)), rtol=1e-6)

    @pytest.mark.parametrize("policy", [
        # full-remat T5 recompile ~9s; the nothing_saveable policy stays
        # tier-1 via test_llama.py::test_remat_matches_no_remat — full
        # run via check_all --all
        pytest.param("nothing_saveable", marks=pytest.mark.slow),
        pytest.param("dots_saveable", marks=pytest.mark.slow),
        # 870s-cap headroom: BOTH T5 remat policies now ride
        # check_all --all; tier-1 remat parity stays pinned via
        # test_llama.py::test_remat_matches_no_remat
    ])
    def test_remat_matches_no_remat(self, tiny, policy):
        """Remat (full or selective) must not change loss or grads."""
        import dataclasses
        cfg, model, params, enc, dec = tiny
        model_r = T5(dataclasses.replace(cfg, remat=True,
                                         remat_policy=policy))
        l1, g1 = jax.value_and_grad(t5_loss_fn(model))(params, enc, dec)
        l2, g2 = jax.value_and_grad(t5_loss_fn(model_r))(params, enc,
                                                         dec)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(g1),
                        jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # ~14s grad compile for a config-plumbing check;
    # tied-head loss/grad parity stays tier-1 via test_loss_fn/
    # test_fused_head_matches_gold_and_grads_alive; full via check_all --all
    def test_untied_head(self):
        cfg = T5Config.tiny(policy=get_policy("O0"),
                            tie_word_embeddings=False,
                            vocab_size=64, d_model=16, num_heads=2,
                            head_dim=8, d_ff=32, num_encoder_layers=1,
                            num_decoder_layers=1)
        model = T5(cfg)
        rng = np.random.default_rng(3)
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 6)),
                          jnp.int32)
        dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 5)),
                          jnp.int32)
        params = model.init(jax.random.key(1), enc, dec)["params"]
        assert "lm_head" in params
        g = jax.grad(t5_loss_fn(model))(params, enc, dec)
        assert float(jnp.max(jnp.abs(g["lm_head"]))) > 0

    # TP-sharded loss parity lives in
    # test_models.py::TestParamSpecs::test_t5_specs (the shared harness
    # GPT-2/BERT use).

    @pytest.mark.slow  # ~26s two whole-model grad compiles; the
    # COMPOSITION check. Halves pinned tier-1: per-op pallas-vs-xla
    # parity (incl. the bias-bearing flash fwd/bwd and segment-ids
    # paths) in test_ops.py/test_attention.py, and the regression this
    # test once caught — the (B,1,1,Sk) mask shape — is covered by the
    # encoder-pad invariance pin above. Runs via check_all --all.
    def test_pallas_xla_parity(self, tiny):
        """Whole-model loss AND grads, Pallas kernels (interpret on CPU)
        vs XLA composites — WITH a padded encoder batch, so the
        bias-bearing flash self-attention, the rel-pos dbias pass, and
        the segment-ids key-padding path are all on the Pallas route
        (a (B,1,1,Sk) mask once crashed exactly here)."""
        from apex1_tpu.ops import _common
        cfg, model, params, enc, dec = tiny
        mask = jnp.asarray([[True] * 9 + [False] * 3, [True] * 12])

        def loss_grads(impl):
            def f(params):
                with _common.force_impl(impl):
                    return t5_loss_fn(model)(params, enc, dec,
                                             enc_pad_mask=mask)
            return jax.value_and_grad(f)(params)

        lp, gp = loss_grads("pallas")
        lx, gx = loss_grads("xla")
        np.testing.assert_allclose(float(lp), float(lx), rtol=2e-4)
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(gp),
                jax.tree_util.tree_leaves_with_path(gx)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
                err_msg=jax.tree_util.keystr(path))


class TestT5AmpStep:
    @pytest.mark.slow  # training loop; the O2 step math is parity-covered
    def test_o2_fused_adam_learns(self, tiny):
        from apex1_tpu.amp import Amp
        from apex1_tpu.optim.fused_adam import fused_adam

        cfg, _, _, enc, dec = tiny
        import dataclasses
        cfg16 = dataclasses.replace(cfg, policy=get_policy("O2"))
        model = T5(cfg16)
        params = model.init(jax.random.key(0), enc, dec)["params"]
        amp = Amp(tx=fused_adam(1e-3), opt_level="O2")
        state = amp.init(params)
        step = jax.jit(amp.make_train_step(t5_loss_fn(model)))
        losses = []
        for _ in range(8):
            state, metrics = step(state, enc, dec)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses


@pytest.mark.slow
class TestT5Pipeline:
    """Pipelined enc-dec over pp=4 (2 encoder + 2 decoder stages), one
    pad-to-max boundary carrying [encoder rows | decoder rows] — the
    compiled-SPMD realization of the reference's variable-shape
    ``_communicate`` (SURVEY #56). Loss and every real parameter's grad
    must match the flat model."""

    def _build(self):
        cfg = T5Config.tiny(policy=get_policy("O0"))
        model = T5(cfg)
        rng = np.random.default_rng(11)
        B, S_enc, S_dec = 4, 12, 9
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S_enc)),
                          jnp.int32)
        dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S_dec)),
                          jnp.int32)
        params = model.init(jax.random.key(0), enc, dec)["params"]
        return cfg, model, params, enc, dec

    def test_pipelined_matches_flat(self, devices):
        from jax.sharding import PartitionSpec as Ps

        cfg, model, params, enc_tokens, dec_tokens = self._build()
        E_STAGES, P_STAGES, M = 2, 4, 4
        B, S_enc = enc_tokens.shape
        S_di = dec_tokens.shape[1] - 1          # teacher-forced input len
        S_dmax = S_di + 4   # boundary sized for a LONGER max decoder
        #                     extent than this batch uses — the
        #                     decoder_seq_length pad-to-max scenario;
        #                     pipeline_apply zero-pads the injected
        #                     microbatches into the wider boundary
        Dm = cfg.d_model
        mesh = make_mesh(pp=P_STAGES)

        # ---- uniform per-stage param tree (zeros where a stage has no
        # such block; dead leaves get zero grads) ----
        def zeros_like_tree(t):
            return jax.tree_util.tree_map(jnp.zeros_like, t)

        enc_layers = [params["encoder"][f"layer{i}"] for i in range(2)]
        dec_layers = [params["decoder"][f"layer{i}"] for i in range(2)]
        stage_trees = []
        for s in range(P_STAGES):
            is_enc = s < E_STAGES
            stage_trees.append({
                "enc_block": (enc_layers[s] if is_enc
                              else zeros_like_tree(enc_layers[0])),
                "dec_block": (dec_layers[s - E_STAGES] if not is_enc
                              else zeros_like_tree(dec_layers[0])),
                "enc_rel": params["encoder"]["rel_pos"]["rel_bias"],
                "dec_rel": params["decoder"]["rel_pos"]["rel_bias"],
                "enc_final": params["encoder"]["final_norm"],
                "dec_final": params["decoder"]["final_norm"],
            })
        # stack stage-major then add the V=1 chunk axis
        chunk_params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs)[None], *stage_trees)

        from apex1_tpu.models.t5 import _causal_mask
        from apex1_tpu.ops import rms_norm

        def stage_fn(w, x):
            """x: (mb, S_enc + S_dmax, Dm) — the pad-to-max boundary.
            Encoder stages transform the encoder rows; decoder stages
            transform their real S_di-row extent with cross-attention
            into the (final) encoder rows; the dead max-extent tail
            passes through as zeros."""
            s = jax.lax.axis_index("pp")
            xe = x[:, :S_enc]
            xd = x[:, S_enc:S_enc + S_di]
            tail = x[:, S_enc + S_di:]
            enc_bias = RelPosBias(cfg, bidirectional=True).apply(
                {"params": {"rel_bias": w["enc_rel"]}}, S_enc, S_enc)
            dec_bias = RelPosBias(cfg, bidirectional=False).apply(
                {"params": {"rel_bias": w["dec_rel"]}}, S_di, S_di)
            dec_bias = dec_bias + _causal_mask(S_di, S_di)

            ye = T5Block(cfg, is_decoder=False).apply(
                {"params": w["enc_block"]}, xe, enc_bias)
            ye = jnp.where(s == E_STAGES - 1,
                           rms_norm(ye, w["enc_final"], eps=cfg.norm_eps),
                           ye)
            yd = T5Block(cfg, is_decoder=True).apply(
                {"params": w["dec_block"]}, xd, dec_bias, memory=xe)
            yd = jnp.where(s == P_STAGES - 1,
                           rms_norm(yd, w["dec_final"], eps=cfg.norm_eps),
                           yd)
            is_enc = s < E_STAGES
            return jnp.concatenate(
                [jnp.where(is_enc, ye, xe), jnp.where(is_enc, xd, yd),
                 tail], axis=1)

        def pipe_loss(chunk_params, emb):
            xe = emb[enc_tokens]
            xd = emb[dec_tokens[:, :-1]]
            x = jnp.concatenate([xe, xd], axis=1)        # (B, S_tot, Dm)
            mbs = x.reshape(M, B // M, S_enc + S_di, Dm)

            def inner(chunk_params, mbs):
                local = jax.tree_util.tree_map(lambda p: p[:, 0],
                                               chunk_params)
                return schedules.pipeline_apply(
                    stage_fn, local, mbs,
                    boundary_shape=(B // M, S_enc + S_dmax, Dm))

            outs = jax.shard_map(
                inner, mesh=mesh, in_specs=(Ps(None, "pp"), Ps()),
                out_specs=Ps(), check_vma=False)(chunk_params, mbs)
            outs = outs[:, :, :S_enc + S_di]     # drop the dead tail
            h_dec = outs.reshape(B, S_enc + S_di, Dm)[:, S_enc:]
            w_head = emb * cfg.d_model ** -0.5
            logits = jnp.einsum("bsh,vh->bsv", h_dec, w_head)
            from apex1_tpu.ops import softmax_cross_entropy_loss
            return jnp.mean(softmax_cross_entropy_loss(
                logits, dec_tokens[:, 1:]))

        emb = params["shared_embedding"]
        loss_p, (g_stage, g_emb) = jax.value_and_grad(
            pipe_loss, argnums=(0, 1))(chunk_params, emb)

        flat_loss_fn = t5_loss_fn(model, fuse_head=False)
        loss_f = flat_loss_fn(params, enc_tokens, dec_tokens)
        g_flat = jax.grad(flat_loss_fn)(params, enc_tokens, dec_tokens)

        np.testing.assert_allclose(float(loss_p), float(loss_f),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_emb),
                                   np.asarray(g_flat["shared_embedding"]),
                                   rtol=2e-4, atol=1e-5)
        for i in range(2):
            got = jax.tree_util.tree_map(lambda p: p[0, i],
                                         g_stage["enc_block"])
            want = g_flat["encoder"][f"layer{i}"]
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
                got, want)
            got = jax.tree_util.tree_map(lambda p: p[0, 2 + i],
                                         g_stage["dec_block"])
            want = g_flat["decoder"][f"layer{i}"]
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
                got, want)
        # rel tables + final norms: per-stage copies sum to the flat grad
        np.testing.assert_allclose(
            np.asarray(jnp.sum(g_stage["enc_rel"][0], axis=0)),
            np.asarray(g_flat["encoder"]["rel_pos"]["rel_bias"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(g_stage["dec_rel"][0], axis=0)),
            np.asarray(g_flat["decoder"]["rel_pos"]["rel_bias"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(g_stage["enc_final"][0], axis=0)),
            np.asarray(g_flat["encoder"]["final_norm"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(g_stage["dec_final"][0], axis=0)),
            np.asarray(g_flat["decoder"]["final_norm"]),
            rtol=2e-4, atol=1e-5)
