"""Ring attention (context parallelism) vs global attention on the 8-device
CPU mesh — fwd + grads, causal + segments (SURVEY.md §5.7 build obligation:
BASELINE config 5 long-context capability the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.parallel.ring_attention import ring_attention

B, H, S, D = 2, 2, 64, 16
SP = 4  # ring size


def _mk(rng, dtype=jnp.float32):
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), dtype)
    return q, k, v


def _ring_fn(mesh, causal, with_segs=False):
    spec = P(None, None, "cp", None)
    segspec = P(None, "cp")
    in_specs = (spec, spec, spec) + ((segspec,) if with_segs else ())

    def local(q, k, v, *segs):
        return ring_attention(q, k, v, "cp", causal=causal,
                              segment_ids=segs[0] if segs else None)

    return jax.jit(jax.shard_map(local, mesh=mesh, in_specs=in_specs,
                                 out_specs=spec))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_global(rng, causal, devices):
    mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
    q, k, v = _mk(rng)
    got = _ring_fn(mesh, causal)(q, k, v)
    want = flash_attention(q, k, v, causal=causal)  # xla gold on cpu
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_with_segments(rng, devices):
    mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
    q, k, v = _mk(rng)
    seg = jnp.sort(jnp.asarray(rng.integers(0, 3, size=(B, S)), jnp.int32),
                   axis=1)
    got = _ring_fn(mesh, True, with_segs=True)(q, k, v, seg)
    want = flash_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_global(rng, causal, devices):
    mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
    q, k, v = _mk(rng)
    ring = _ring_fn(mesh, causal)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring(q, k, v)))

    def loss_global(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, causal=causal)))

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_global, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)


def test_ring_gqa(rng, devices):
    mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
    q = jnp.asarray(rng.normal(size=(B, 4, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, 2, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, 2, S, D)), jnp.float32)
    spec = P(None, None, "cp", None)
    fn = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "cp", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    got = fn(q, k, v)
    want = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestOverlappedSchedule:
    """The double-buffered rewrite against its anchors: bit-for-bit
    forward parity with the retained serialized schedule (same
    attend/merge order — only the permutes' dataflow moved), and grad
    parity with the global gold through BOTH backward paths (the
    custom-VJP overlapped ring and XLA's transpose of the scan)."""

    def test_fwd_bitwise_matches_serial(self, rng, devices):
        from apex1_tpu.parallel.ring_attention import ring_attention_serial
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q, k, v = _mk(rng)
        seg = jnp.sort(jnp.asarray(rng.integers(0, 3, size=(B, S)),
                                   jnp.int32), axis=1)
        spec = P(None, None, "cp", None)
        segspec = P(None, "cp")

        def mk(fn):
            return jax.jit(jax.shard_map(
                lambda q, k, v, s: fn(q, k, v, "cp", causal=True,
                                      segment_ids=s),
                mesh=mesh, in_specs=(spec,) * 3 + (segspec,),
                out_specs=spec))

        got = mk(ring_attention)(q, k, v, seg)
        ser = mk(ring_attention_serial)(q, k, v, seg)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ser))

    @pytest.mark.parametrize("use_custom_vjp", [True, False])
    def test_grads_both_vjp_paths(self, rng, devices, use_custom_vjp):
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q, k, v = _mk(rng)
        spec = P(None, None, "cp", None)
        ring = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=True,
                                           use_custom_vjp=use_custom_vjp),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
        got = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v))),
            argnums=(0, 1, 2)))(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(
                flash_attention(q, k, v, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    def test_gqa_grads_match_global(self, rng, devices):
        """GQA through the custom backward: the per-shard dk/dv group
        reduction must match the unsharded gold."""
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q = jnp.asarray(rng.normal(size=(B, 4, S, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, 2, S, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, 2, S, D)), jnp.float32)
        spec = P(None, None, "cp", None)
        ring = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
        got = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v))),
            argnums=(0, 1, 2)))(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(
                flash_attention(q, k, v, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    def test_segment_grads_ride_the_bwd_ring(self, rng, devices):
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q, k, v = _mk(rng)
        seg = jnp.sort(jnp.asarray(rng.integers(0, 3, size=(B, S)),
                                   jnp.int32), axis=1)
        spec = P(None, None, "cp", None)
        ring = jax.shard_map(
            lambda q, k, v, s: ring_attention(q, k, v, "cp", causal=True,
                                              segment_ids=s),
            mesh=mesh, in_specs=(spec,) * 3 + (P(None, "cp"),),
            out_specs=spec)
        got = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v, seg))),
            argnums=(0, 1, 2)))(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(flash_attention(
                q, k, v, causal=True, segment_ids=seg))),
            argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    def test_pallas_step_backward_interpret(self, rng, devices):
        """Execute the PALLAS branch of the ring backward (interpret
        mode on the CPU mesh): the CPU suite otherwise only runs
        `_step_grads_xla`, while TPU training runs only
        `_step_grads_pallas` — a wiring bug in its res/lse-padding/
        dlse=0 handling must not ship numerics-untested."""
        from apex1_tpu.ops import force_impl
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q = jnp.asarray(rng.normal(size=(1, 2, S, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, S, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 1, S, 16)), jnp.float32)
        spec = P(None, None, "cp", None)

        def local(q, k, v):
            with force_impl("pallas"):
                return ring_attention(q, k, v, "cp", causal=True)

        ring = jax.shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec, check_vma=False)
        got = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v))),
            argnums=(0, 1, 2)))(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(
                flash_attention(q, k, v, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)

    def test_ring_size_two(self, rng, devices):
        """cp=2 exercises both peeled edges (empty scan bodies)."""
        mesh = make_mesh(cp=2, dp=1, devices=devices[:2])
        q, k, v = _mk(rng)
        spec = P(None, None, "cp", None)
        ring = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "cp", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
        got = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v))),
            argnums=(0, 1, 2)))(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(
                flash_attention(q, k, v, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)


class TestUlysses:
    """All-to-all sequence parallelism (≙ DeepSpeed Ulysses; SURVEY §2.6
    [absent] in apex): head-scatter attention over cp must equal
    unsharded flash attention on the full sequence."""

    def test_matches_unsharded(self, rng, devices):
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ulysses import ulysses_attention
        B, H, S, D = 2, 4, 64, 16
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
                   for _ in range(3))

        def f(q, k, v):
            return ulysses_attention(q, k, v, "cp", causal=True)

        got = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"), check_vma=False))(q, k, v)
        want = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_segment_ids_ride_along(self, rng, devices):
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ulysses import ulysses_attention
        B, H, S, D = 1, 4, 32, 8
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
                   for _ in range(3))
        segs = jnp.asarray(
            np.repeat(np.arange(4), 8)[None, :], jnp.int32)  # 4 docs

        def f(q, k, v, s):
            return ulysses_attention(q, k, v, "cp", causal=True,
                                     segment_ids=s)

        got = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=((P(None, None, "cp"),) * 3 + (P(None, "cp"),)),
            out_specs=P(None, None, "cp"), check_vma=False))(q, k, v, segs)
        want = flash_attention(q, k, v, causal=True, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_head_divisibility_error(self, rng, devices):
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ulysses import ulysses_attention
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        q = jnp.ones((1, 2, 16, 8), jnp.float32)  # 2 heads, cp=4

        def f(q):
            return ulysses_attention(q, q, q, "cp")

        with pytest.raises(ValueError, match="divisible"):
            jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=(P(None, None, "cp"),),
                out_specs=P(None, None, "cp"), check_vma=False))(q)

    def test_ring_fallback_on_indivisible_heads(self, rng, devices):
        """fallback='ring' routes head counts ulysses cannot shard
        through the overlapped ring instead of raising — same numerics
        as unsharded flash."""
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ulysses import ulysses_attention
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        q = jnp.asarray(rng.normal(size=(1, 2, 32, 8)), jnp.float32)

        def f(q):
            return ulysses_attention(q, q, q, "cp", causal=True,
                                     fallback="ring")

        got = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, None, "cp"),),
            out_specs=P(None, None, "cp"), check_vma=False))(q)
        want = flash_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_llama_ulysses_cp(self, rng, devices):
        """Llama with cp_impl='ulysses': sharded forward == unsharded."""
        import dataclasses

        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.models.llama import Llama, LlamaConfig
        cfg = dataclasses.replace(LlamaConfig.tiny(), cp_impl="ulysses")
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 32)),
                             jnp.int32)
        plain = Llama(cfg)
        sharded_model = Llama(cfg, seq_shard_axis="cp")
        params = plain.init(jax.random.key(0), tokens)["params"]
        want = plain.apply({"params": params}, tokens)

        def f(p, t):
            return sharded_model.apply({"params": p}, t)

        got = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P(None, "cp")),
            out_specs=P(None, "cp"), check_vma=False))(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    def test_grads_match_unsharded(self, rng, devices):
        """AD through the double all_to_all: dq/dk/dv under cp=4 equal
        the unsharded flash attention gradients."""
        from jax.sharding import PartitionSpec as P

        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.parallel.ulysses import ulysses_attention
        B, H, S, D = 1, 4, 32, 8
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
                   for _ in range(3))

        smapped = jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "cp", causal=True),
            mesh=mesh, in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"), check_vma=False)

        g_ep = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(smapped(q, k, v) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ep, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestRingDropout:
    """In-kernel dropout over the ring (PR 5): the counter-based mask
    keys on GLOBAL positions via each shard's k_offset, so (a) serial
    and overlapped schedules drop identical weights, (b) the sharded
    result equals single-device flash dropout, (c) both custom-VJP
    paths agree. The tolerance is tight-allclose, not bitwise: the two
    schedules compile to different programs and differ by float
    rounding only (a wrong mask would differ by O(1) dropped weights)."""

    P_DROP, SEED = 0.2, 99

    def _run(self, mesh, fn, q, k, v, **kw):
        spec = P(None, None, "cp", None)

        def local(q, k, v):
            return fn(q, k, v, "cp", causal=True, dropout_p=self.P_DROP,
                      dropout_seed=self.SEED, **kw)

        return jax.jit(jax.shard_map(local, mesh=mesh,
                                     in_specs=(spec,) * 3,
                                     out_specs=spec))(q, k, v)

    def test_serial_overlapped_parity_and_flash_equivalence(self, rng,
                                                            devices):
        from apex1_tpu.parallel.ring_attention import ring_attention_serial
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q, k, v = _mk(rng)
        o_ov = self._run(mesh, ring_attention, q, k, v)
        o_se = self._run(mesh, ring_attention_serial, q, k, v)
        np.testing.assert_allclose(o_ov, o_se, rtol=5e-6, atol=5e-7)
        # sharded == unsharded: the mask is a pure function of global
        # position, so context parallelism does not change WHICH
        # weights drop — only how the sum is sliced
        want = flash_attention(q, k, v, causal=True,
                               dropout_p=self.P_DROP,
                               dropout_seed=self.SEED)
        np.testing.assert_allclose(o_ov, want, rtol=2e-5, atol=2e-5)
        # and dropout actually happened
        plain = flash_attention(q, k, v, causal=True)
        assert not np.allclose(o_ov, plain, atol=1e-3)
        # causal-skip cond off (tools/bench_cond_elision.py's A/B arm):
        # numerics identical
        o_ns = self._run(mesh, ring_attention, q, k, v,
                         skip_masked=False)
        np.testing.assert_allclose(o_ns, o_ov, rtol=1e-6, atol=1e-7)

    @pytest.mark.slow  # two full ring-backward compiles: check_all --all
    def test_grads_both_vjp_paths(self, rng, devices):
        mesh = make_mesh(cp=SP, dp=1, devices=devices[:SP])
        q, k, v = _mk(rng)
        spec = P(None, None, "cp", None)

        def grads(use_custom):
            def local(q, k, v):
                return ring_attention(q, k, v, "cp", causal=True,
                                      dropout_p=self.P_DROP,
                                      dropout_seed=self.SEED,
                                      use_custom_vjp=use_custom)

            sm = jax.shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                               out_specs=spec)
            return jax.grad(lambda q, k, v: jnp.sum(sm(q, k, v) ** 2),
                            (0, 1, 2))(q, k, v)

        for a, b in zip(grads(True), grads(False)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)

