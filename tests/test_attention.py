"""Flash attention parity tests — Pallas kernel (interpret mode on the CPU
harness) vs the XLA composite gold, fwd + grads.

Reference test analogue: ``apex/contrib/test/fmha/test_fmha.py`` and
``apex/contrib/test/multihead_attn/*`` — hand-written python attention as
gold, per-kernel allclose at dtype tolerances (SURVEY.md §4.2.1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.ops import force_impl
from apex1_tpu.ops.attention import flash_attention, fmha


def _qkv(rng, B=2, Hq=2, Hkv=None, Sq=48, Sk=None, D=16, dtype=jnp.float32):
    Hkv = Hq if Hkv is None else Hkv
    Sk = Sq if Sk is None else Sk
    q = jnp.asarray(rng.normal(size=(B, Hq, Sq, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, Hkv, Sk, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, Hkv, Sk, D)), dtype)
    return q, k, v


def _run(q, k, v, impl, **kw):
    with force_impl(impl):
        return flash_attention(q, k, v, **kw)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("gqa", [False, True])
def test_forward_parity(rng, causal, gqa):
    q, k, v = _qkv(rng, Hq=4, Hkv=2 if gqa else 4)
    got = _run(q, k, v, "pallas", causal=causal)
    want = _run(q, k, v, "xla", causal=causal)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_forward_parity_bf16(rng):
    q, k, v = _qkv(rng, dtype=jnp.bfloat16)
    got = _run(q, k, v, "pallas", causal=True).astype(jnp.float32)
    want = _run(q, k, v, "xla", causal=True).astype(jnp.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_cross_attention_shapes(rng):
    q, k, v = _qkv(rng, Sq=24, Sk=56)
    got = _run(q, k, v, "pallas")
    want = _run(q, k, v, "xla")
    assert got.shape == (2, 2, 24, 16)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(rng, causal):
    q, k, v = _qkv(rng, Sq=40)
    w = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(_run(q, k, v, impl, causal=causal) * w)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for g, gg in zip(loss("pallas"), loss("xla")):
        np.testing.assert_allclose(g, gg, rtol=1e-4, atol=1e-4)


def test_grad_parity_gqa(rng):
    q, k, v = _qkv(rng, Hq=4, Hkv=2)

    def grads(impl):
        def f(q, k, v):
            return jnp.sum(jnp.square(_run(q, k, v, impl, causal=True)))
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for g, gg in zip(grads("pallas"), grads("xla")):
        np.testing.assert_allclose(g, gg, rtol=1e-4, atol=1e-4)


def test_segment_ids_varlen(rng):
    """Segments ≙ fmha's cu_seqlens: packed batch matches separate calls."""
    B, H, D = 1, 2, 16
    s1, s2 = 20, 28
    q, k, v = _qkv(rng, B=B, Hq=H, Sq=s1 + s2, D=D)
    seg = jnp.asarray([[0] * s1 + [1] * s2], jnp.int32)
    got = _run(q, k, v, "pallas", causal=True, segment_ids=seg)
    want = _run(q, k, v, "xla", causal=True, segment_ids=seg)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # piecewise reference: each segment attends only to itself
    for lo, hi in ((0, s1), (s1, s1 + s2)):
        piece = _run(q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi],
                     "xla", causal=True)
        np.testing.assert_allclose(got[:, :, lo:hi], piece,
                                   rtol=1e-5, atol=1e-5)


def test_segment_grad_parity(rng):
    q, k, v = _qkv(rng, B=2, Sq=32)
    seg = jnp.asarray(rng.integers(0, 3, size=(2, 32)), jnp.int32)
    seg = jnp.sort(seg, axis=1)

    def grads(impl):
        def f(q, k, v):
            return jnp.sum(jnp.square(
                _run(q, k, v, impl, segment_ids=seg)))
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for g, gg in zip(grads("pallas"), grads("xla")):
        np.testing.assert_allclose(g, gg, rtol=1e-4, atol=1e-4)


def test_causal_offsets(rng):
    """Offsets shift the global causal positions (ring-attention blocks)."""
    S = 32
    q, k, v = _qkv(rng, B=1, Sq=S)
    # q shard holding global rows [32, 64), k shard holding cols [0, 32):
    # fully visible under causal → equals non-causal attention
    got = _run(q, k, v, "pallas", causal=True, q_offset=S, k_offset=0)
    want = _run(q, k, v, "xla", causal=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # k shard strictly in the future → fully masked, zero output, -inf lse
    out, lse = _run(q, k, v, "pallas", causal=True, q_offset=0, k_offset=S,
                    return_lse=True)
    np.testing.assert_allclose(out, jnp.zeros_like(out))
    assert np.all(np.asarray(lse) < -1e29)


def test_lse_and_its_grad(rng):
    """return_lse parity + the dlse VJP path (ring-merge differentiability)."""
    q, k, v = _qkv(rng, Sq=32)
    with force_impl("pallas"):
        out_p, lse_p = flash_attention(q, k, v, causal=True, return_lse=True)
    with force_impl("xla"):
        out_x, lse_x = flash_attention(q, k, v, causal=True, return_lse=True)
    np.testing.assert_allclose(lse_p, lse_x, rtol=1e-5, atol=1e-5)

    def loss(impl):
        def f(q, k, v):
            with force_impl(impl):
                out, lse = flash_attention(q, k, v, causal=True,
                                           return_lse=True)
            return jnp.sum(jnp.square(out)) + jnp.sum(jnp.sin(lse))
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for g, gg in zip(loss("pallas"), loss("xla")):
        np.testing.assert_allclose(g, gg, rtol=1e-4, atol=1e-4)


def test_fmha_packed(rng):
    B, S, H, D = 2, 24, 2, 16
    qkv = jnp.asarray(rng.normal(size=(B, S, 3, H, D)), jnp.float32)
    with force_impl("pallas"):
        got = fmha(qkv, causal=True)
    with force_impl("xla"):
        want = fmha(qkv, causal=True)
    assert got.shape == (B, S, H, D)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("q_off,k_off", [(0, 0), (32, 0), (0, 32), (48, 16)])
def test_multiblock_causal_skip(rng, q_off, k_off):
    """Small explicit blocks force a multi-block grid so the causal
    block-skip predicate (fully-above-diagonal blocks bypassed) is
    exercised on every class of block: skipped, diagonal-partial, and
    fully-live — including shifted diagonals from ring-style offsets."""
    q, k, v = _qkv(rng, Sq=96, Sk=96)
    kw = dict(causal=True, q_offset=q_off, k_offset=k_off,
              block_q=16, block_k=32)

    def loss(impl):
        def f(q, k, v):
            with force_impl(impl):
                out = flash_attention(q, k, v, **kw)
            return jnp.sum(jnp.square(out.astype(jnp.float32)))
        return jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)

    (lp, gp), (lx, gx) = loss("pallas"), loss("xla")
    np.testing.assert_allclose(lp, lx, rtol=1e-5)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestAdditiveBias:
    """The flash kernel's additive-``bias`` operand (T5 rel-pos path):
    fwd and all four grads — including dbias through the dedicated
    broadcast-accumulating backward pass — must match the biased XLA
    composite for every broadcast layout."""

    @pytest.mark.parametrize("cfg", [
        dict(B=2, Hq=4, Hkv=4, Sq=48, Sk=48, Bb=1, Hb=4, causal=False),
        dict(B=2, Hq=4, Hkv=2, Sq=48, Sk=48, Bb=1, Hb=4, causal=True),
        dict(B=2, Hq=4, Hkv=4, Sq=40, Sk=56, Bb=2, Hb=4, causal=False),
        dict(B=1, Hq=2, Hkv=2, Sq=33, Sk=47, Bb=1, Hb=1, causal=False),
        dict(B=2, Hq=2, Hkv=2, Sq=96, Sk=96, Bb=1, Hb=2, causal=True,
             blocks=(16, 32)),  # multi-block grid + causal block skip
    ], ids=["full", "gqa-causal", "cross-batchbias", "ragged-bcast",
            "multiblock"])
    def test_grads_match_xla(self, rng, cfg):
        q, k, v = _qkv(rng, B=cfg["B"], Hq=cfg["Hq"], Hkv=cfg["Hkv"],
                       Sq=cfg["Sq"], Sk=cfg["Sk"], D=32)
        bias = jnp.asarray(
            rng.normal(size=(cfg["Bb"], cfg["Hb"], cfg["Sq"],
                             cfg["Sk"])), jnp.float32)
        kw = dict(causal=cfg["causal"], bias=bias)
        if "blocks" in cfg:
            kw.update(block_q=cfg["blocks"][0], block_k=cfg["blocks"][1])

        def loss(impl):
            def f(q, k, v, b):
                with force_impl(impl):
                    out = flash_attention(q, k, v, causal=cfg["causal"],
                                          bias=b,
                                          **({k_: v_ for k_, v_ in
                                              kw.items()
                                              if k_.startswith("block")}))
                return jnp.sum(jnp.square(out.astype(jnp.float32)))
            return jax.value_and_grad(f, argnums=(0, 1, 2, 3))(q, k, v,
                                                               bias)

        (lp, gp), (lx, gx) = loss("pallas"), loss("xla")
        np.testing.assert_allclose(lp, lx, rtol=1e-5)
        for name, a, b in zip(("dq", "dk", "dv", "dbias"), gp, gx):
            # dbias sums over batch x blocks: accumulation-order noise
            # ~1e-5 shows up at near-zero-gradient positions
            np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=5e-5 if name == "dbias" else 1e-5,
                err_msg=name)

    def test_bias_with_segments(self, rng):
        """bias composes with varlen segment masking."""
        q, k, v = _qkv(rng, Sq=48)
        segs = jnp.asarray(
            np.repeat(np.arange(3), 16)[None].repeat(2, 0), jnp.int32)
        bias = jnp.asarray(rng.normal(size=(1, 2, 48, 48)), jnp.float32)

        def run(impl):
            def f(q, k, v, b):
                with force_impl(impl):
                    out = flash_attention(q, k, v, segment_ids=segs,
                                          bias=b)
                return jnp.sum(jnp.square(out.astype(jnp.float32)))
            return jax.value_and_grad(f, argnums=(0, 3))(q, k, v, bias)

        (lp, gp), (lx, gx) = run("pallas"), run("xla")
        np.testing.assert_allclose(lp, lx, rtol=1e-5)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_bad_bias_shapes_raise(self, rng):
        q, k, v = _qkv(rng)
        with force_impl("pallas"):
            with pytest.raises(ValueError, match="bias"):
                flash_attention(q, k, v,
                                bias=jnp.zeros((3, 2, 48, 48)))
            with pytest.raises(ValueError, match="bias"):
                flash_attention(q, k, v, bias=jnp.zeros((48, 48)))
