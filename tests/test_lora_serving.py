"""Multi-tenant LoRA serving (``serving.lora`` + the engine's fused
logits epilogue) — the ISSUE 19 acceptance spine: one engine batch
mixing LoRA-on slots across two adapters with an adapterless control
must emit token streams BIT-IDENTICAL to per-tenant solo runs, across
the dense, paged-gold, paged-kernel, and speculative paths, with the
usual two executables and no retraces.  Plus the store's page-lifetime
control plane (the APX202 publish discipline's host half) and the
fleetsim noisy-tenant isolation drill that maps tenants onto QoS
classes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.models.generate import llama_decoder
from apex1_tpu.models.llama import Llama, LlamaConfig
from apex1_tpu.ops import _common
from apex1_tpu.serving.engine import Engine, EngineConfig
from apex1_tpu.serving.lora import LoraAdapterStore

RANK = 2

# two tenants share a prompt with the adapterless control: if the
# adapters were inert the parity assertions would prove nothing
PROMPTS = {101: ([3, 1, 4, 1, 5], "tenant-a"),
           102: ([2, 7, 1, 8], "tenant-b"),
           103: ([3, 1, 4, 1, 5], None)}


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, ffn_size=64,
                      max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    apply_fn, make_cache = llama_decoder(model)
    k = jax.random.key(1)
    adapters = {}
    for name in ("tenant-a", "tenant-b"):
        k, ka, kb = jax.random.split(k, 3)
        adapters[name] = (
            jax.random.normal(ka, (cfg.hidden_size, RANK)) * 0.2,
            jax.random.normal(kb, (RANK, cfg.vocab_size)) * 0.2)
    return cfg, params, apply_fn, make_cache, adapters


def _engine(tiny, **kw):
    cfg, params, apply_fn, make_cache, adapters = tiny
    ekw = dict(max_slots=4, max_len=32, prefill_chunk=4,
               temperature=0.7, seed=7, lora_rank=RANK,
               lora_max_adapters=4)
    ekw.update(kw)
    eng = Engine(apply_fn, make_cache, params, EngineConfig(**ekw),
                 lora_head=params["output"])
    for name, (A, B) in adapters.items():
        eng.register_adapter(name, A, B, scale=2.0)
    return eng


def _run(eng, active):
    for rid in sorted(active):
        toks, tenant = PROMPTS[rid]
        eng.submit(np.asarray(toks, np.int32), 8, req_id=rid,
                   tenant=tenant, seed=1000 + rid)
    eng.run(max_steps=100)
    return {rid: list(eng.results[rid].tokens) for rid in active}


# ---------------------------------------------------------------------------
# the adapter-page store: lifetime control plane
# ---------------------------------------------------------------------------


class TestLoraAdapterStore:
    def _store(self, **kw):
        kws = dict(hidden=8, vocab=16, rank=2, max_adapters=2)
        kws.update(kw)
        return LoraAdapterStore(**kws)

    def _ab(self, st, seed=0):
        rng = np.random.default_rng(seed)
        return (rng.normal(size=(st.hidden, st.rank)),
                rng.normal(size=(st.rank, st.vocab)))

    def test_register_acquire_release_refcounts(self):
        st = self._store()
        pages = st.register("acme", *self._ab(st))
        assert len(pages) == st.rank and 0 not in pages
        assert all(st.page_refcount(p) == 1 for p in pages)

        row, on = st.acquire("acme", slot=0)
        assert on and list(row) == list(pages)
        row2, on2 = st.acquire("acme", slot=1)
        assert on2
        assert all(st.page_refcount(p) == 3 for p in pages)

        # unregister drops only the registry's ref — in-flight slots
        # keep the pages readable (teardown half of the publish race)
        st.unregister("acme")
        assert all(st.page_refcount(p) == 2 for p in pages)
        assert st.n_free == 0 + (st.num_pages - 1 - st.rank)

        st.release(0)
        st.release(1)
        assert all(st.page_refcount(p) == 0 for p in pages)
        assert st.n_free == st.num_pages - 1  # zero page never frees

    def test_duplicate_register_raises(self):
        st = self._store()
        st.register("acme", *self._ab(st))
        with pytest.raises(ValueError, match="already registered"):
            st.register("acme", *self._ab(st))

    def test_shape_validation(self):
        st = self._store()
        A, B = self._ab(st)
        with pytest.raises(ValueError, match="A shape"):
            st.register("x", A.T, B)
        with pytest.raises(ValueError, match="B shape"):
            st.register("x", A, B.T)

    def test_unknown_or_none_adapter_is_zero_row(self):
        st = self._store()
        for who in (None, "ghost"):
            row, on = st.acquire(who, slot=3)
            assert not on and not row.any()
        st.release(3)  # no-op: adapterless slots own nothing

    def test_slot_double_acquire_raises(self):
        st = self._store()
        st.register("acme", *self._ab(st))
        st.acquire("acme", slot=0)
        with pytest.raises(ValueError, match="already holds"):
            st.acquire("acme", slot=0)

    def test_pool_exhaustion_is_loud(self):
        st = self._store(max_adapters=1)
        st.register("acme", *self._ab(st))
        with pytest.raises(RuntimeError, match="out of pages"):
            st.register("zeta", *self._ab(st))
        # sizing invariant: max_adapters registrations can't exhaust
        st.unregister("acme")
        st.register("zeta", *self._ab(st))

    def test_unregister_unknown_raises(self):
        with pytest.raises(KeyError, match="ghost"):
            self._store().unregister("ghost")

    def test_scale_folded_into_b_pages_and_zero_page_stays_zero(self):
        st = self._store()
        A, B = self._ab(st)
        pages = st.register("acme", A, B, scale=4.0)
        for j, pid in enumerate(pages):
            np.testing.assert_allclose(
                np.asarray(st.a_pages[pid]), A.T[j], rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(st.b_pages[pid]),
                B[j] * (4.0 / st.rank), rtol=1e-6)
        assert not np.asarray(st.a_pages[0]).any()
        assert not np.asarray(st.b_pages[0]).any()


# ---------------------------------------------------------------------------
# engine wiring: validation + parity
# ---------------------------------------------------------------------------


class TestEngineLoraValidation:
    def test_lora_rank_requires_head(self, tiny):
        cfg, params, apply_fn, make_cache, _ = tiny
        with pytest.raises(ValueError, match="lora_head"):
            Engine(apply_fn, make_cache, params,
                   EngineConfig(max_slots=2, max_len=32, lora_rank=2))

    def test_config_negatives(self):
        with pytest.raises(ValueError, match="lora_rank"):
            EngineConfig(max_slots=2, max_len=32, lora_rank=-1)
        with pytest.raises(ValueError, match="lora_max_adapters"):
            EngineConfig(max_slots=2, max_len=32, lora_rank=2,
                         lora_max_adapters=0)

    def test_register_without_lora_raises(self, tiny):
        cfg, params, apply_fn, make_cache, _ = tiny
        eng = Engine(apply_fn, make_cache, params,
                     EngineConfig(max_slots=2, max_len=32))
        with pytest.raises(RuntimeError, match="lora"):
            eng.register_adapter("acme", np.zeros((32, 2)),
                                 np.zeros((2, 97)))


class TestLoraEngineParity:
    def test_mixed_batch_bitwise_vs_solo_dense(self, tiny):
        """The acceptance criterion: one batch mixing two adapters and
        an adapterless control == per-tenant solo runs, bit for bit —
        and the adapters really steer the stream (101 and 103 share a
        prompt but must diverge)."""
        mixed = _run(_engine(tiny), set(PROMPTS))
        for rid in PROMPTS:
            assert mixed[rid] == _run(_engine(tiny), {rid})[rid], rid
        assert mixed[101] != mixed[103], \
            "adapter had no effect on the stream"

    def test_two_executables_no_retrace(self, tiny):
        eng = _engine(tiny)
        _run(eng, set(PROMPTS))
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_paged_gold_matches_dense(self, tiny):
        dense = _run(_engine(tiny), set(PROMPTS))
        eng = _engine(tiny, paged=True)
        assert _run(eng, set(PROMPTS)) == dense
        assert eng.trace_counts == {"prefill": 1, "decode": 1}

    def test_paged_kernel_matches_dense(self, tiny):
        """The fused epilogue for real: an engine BUILT under
        force_impl('pallas') routes the adapter delta through the
        `ops.lora_epilogue.lora_delta` kernel (interpret mode on CPU)
        inside the paged decode/prefill epilogues."""
        dense = _run(_engine(tiny), set(PROMPTS))
        with _common.force_impl("pallas"):
            eng = _engine(tiny, paged=True)
            paged = _run(eng, set(PROMPTS))
        assert paged == dense

    def test_speculative_verify_matches_dense(self, tiny):
        """Draft/verify path: the adapter delta lands on every verify
        row (K+1 logits per slot), so accept chains — and therefore
        tokens — match the plain decode engine's exactly when both run
        the same sampling contract."""
        dense = _run(_engine(tiny, num_draft=2), set(PROMPTS))
        for rid in PROMPTS:
            assert dense[rid] == _run(
                _engine(tiny, num_draft=2), {rid})[rid], rid
        eng = _engine(tiny, num_draft=2, paged=True)
        assert _run(eng, set(PROMPTS)) == dense
        assert eng.trace_counts == {"prefill": 1, "verify": 1}

    def test_slots_reusable_after_retire(self, tiny):
        """Adapter pages release at retirement: more requests than
        slots forces reuse; refcounts must return to quiescent."""
        eng = _engine(tiny, max_slots=2)
        out = _run(eng, set(PROMPTS))
        assert len(out) == 3
        st = eng._lora
        assert not st._slot_pages
        assert st.n_free == st.num_pages - 1 - 2 * RANK  # registry refs


# ---------------------------------------------------------------------------
# tenant isolation under noisy-neighbor overload (fleetsim)
# ---------------------------------------------------------------------------


class TestTenantIsolationDrill:
    def test_guaranteed_tenant_holds_slo_under_noisy_overload(self):
        """Tenant=adapter maps onto the QoS ladder: a noisy tenant
        ('zeta') hammering the sheddable class must not drag the
        guaranteed tenant ('acme') below its SLO — the frontend sheds
        the noise instead.  This is the serving-control-plane half of
        multi-tenancy; token-level isolation is the parity suite."""
        from apex1_tpu.autopilot import drill
        from apex1_tpu.testing.fleetsim import (Trace, run_fleet,
                                                synthetic_trace)

        quiet = synthetic_trace(
            "steady", seed=21, horizon_s=3.0, base_rate=6.0,
            class_mix={"guaranteed": 1.0}, tenants=("acme",))
        noisy = synthetic_trace(
            "adversarial_overload", seed=22, horizon_s=3.0,
            base_rate=40.0, overload_mult=3.0,
            class_mix={"sheddable": 1.0}, tenants=("zeta",))
        merged = Trace(
            kind="adversarial_overload", seed=21, horizon_s=3.0,
            requests=sorted(quiet.requests + noisy.requests,
                            key=lambda r: r.t))

        rep = run_fleet(merged, drill.frontend_config(),
                        sim=drill.sim_config())

        att = rep.slo_attainment("guaranteed", drill.SLO_LATENCY_S)
        assert att >= drill.SLO_ATTAINMENT, (
            f"guaranteed attainment {att:.3f} under noisy tenant "
            f"(SLO {drill.SLO_ATTAINMENT})")
        # the isolation was load-bearing: the noisy class really was
        # shed/degraded while the guaranteed class sailed through
        assert rep.rejected.get("sheddable", 0) > 0, rep.summary
        assert rep.rejected.get("guaranteed", 0) == 0, rep.summary
