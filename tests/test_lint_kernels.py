"""graftlint APX2xx suite — the kernel/collective analyzer.

The acceptance spine (ISSUE 11): both PR 9 review-round semaphore
races, re-introduced into fixture copies of the RDMA reduce-scatter
kernel, MUST be flagged with rule ids and line numbers; the shipped
kernel and every other pallas_call site in the repo MUST pass clean;
the n==1 hang check and the registry-shared VMEM model are each pinned
by a falsifiable negative test.

Fixtures run in memory through ``lint_sources(kernels=True)`` like the
APX1xx suite. The protocol fixtures are structural copies of
``ops/fused_collective._mrs_rdma_kernel`` — when that kernel's
protocol changes, change ``GOOD_KERNEL`` here in lockstep (the
repo-wide self-check will hold you to it).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from apex1_tpu.lint import lint_paths, lint_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(src, path="fix/mod.py", modname="fix.mod", **named):
    sources = {path: (modname, textwrap.dedent(src))}
    for p, (m, s) in named.items():
        sources[p] = (m, textwrap.dedent(s))
    return lint_sources(sources, kernels=True)


def codes(res, *, suppressed=False):
    pool = res.suppressed() if suppressed else res.unsuppressed()
    return {f.rule for f in pool}


def line_of(src, marker):
    for i, ln in enumerate(textwrap.dedent(src).splitlines(), 1):
        if marker in ln:
            return i
    raise AssertionError(f"marker {marker!r} not in fixture")


HEADER = """
import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import apex1_tpu
"""

# the protocol body shared by every RDMA fixture, parameterized by the
# slot-reuse block (where both PR 9 races lived) and the credit-signal
# placement
_RDMA_TEMPLATE = HEADER + """
def _kernel(x_ref, w_ref, o_ref, acc_buf, send_buf, send_sem,
            recv_sem, cap_sem, *, n, axis_name):
    t = pl.program_id(0)
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)

    def dev(i):
        return (i,)

    @pl.when(t == 0)
    def _():
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=dev(left))
        pltpu.semaphore_signal(barrier, inc=1, device_id=dev(right))
        pltpu.semaphore_wait(barrier, 2)

    partial = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    slot = jax.lax.rem(t, 2)

    def send_desc(s):
        return pltpu.make_async_remote_copy(
            send_buf.at[s], acc_buf.at[s], send_sem.at[s],
            recv_sem.at[s], device_id=dev(right))

    @pl.when(t == 0)
    def _():
        send_buf[0] = partial

    @pl.when(t > 0)
    def _():
        prev = jax.lax.rem(t + 1, 2)
        pltpu.make_async_remote_copy(
            send_buf.at[prev], acc_buf.at[prev], send_sem.at[prev],
            recv_sem.at[prev], device_id=dev(right)).wait_recv()
%(consume)s
        @pl.when(t == n - 1)
        def _():
            o_ref[...] = ship

    @pl.when(t < n - 1)
    def _():
        send_desc(slot).start()

    @pl.when(t == n - 1)
    def _():
        send_desc(jax.lax.rem(t + 1, 2)).wait_send()

        @pl.when(n > 2)
        def _():
            send_desc(slot).wait_send()


def dispatch(x, w, axis_name="tp"):
    n = jax.lax.axis_size(axis_name)
%(guard)s
    return pl.pallas_call(
        functools.partial(_kernel, n=n, axis_name=axis_name),
        grid=(n,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0)),
                  pl.BlockSpec((128, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
    )(x, w)
"""

_GUARD = """\
    if n < 2:
        raise ValueError("ring of >= 2 devices required")
"""

# the SHIPPED protocol: read, credit only for reused slots, both waits
# before the slot-reuse write
_CONSUME_GOOD = """\
        ship = acc_buf[prev] + partial

        @pl.when(t < n - 2)
        def _():
            pltpu.semaphore_signal(cap_sem, inc=1, device_id=dev(left))

        @pl.when(t < n - 1)
        def _():
            @pl.when(t >= 2)
            def _():
                send_desc(slot).wait_send()
                pltpu.semaphore_wait(cap_sem, 1)
            send_buf[slot] = ship
"""

# PR 9 review round 1, verbatim shape: credit signalled for EVERY t>0
# (n-3 never consumed at n>=4) and the slot-reuse write lands BEFORE
# the send-wait/credit-wait that licenses it
_CONSUME_RACE1 = """\
        ship = acc_buf[prev] + partial
        pltpu.semaphore_signal(cap_sem, inc=1, device_id=dev(left))

        @pl.when(t < n - 1)
        def _():
            send_buf[slot] = ship      # RACE1: write before the waits

            @pl.when(t >= 2)
            def _():
                send_desc(slot).wait_send()
                pltpu.semaphore_wait(cap_sem, 1)
"""

# PR 9 review round 2, verbatim shape: the slot credit returns BEFORE
# acc_buf[prev] is read — an eager upstream overwrites the slot mid-read
_CONSUME_RACE2 = """\
        @pl.when(t < n - 2)
        def _():
            pltpu.semaphore_signal(cap_sem, inc=1, device_id=dev(left))

        ship = acc_buf[prev] + partial  # RACE2: read after credit

        @pl.when(t < n - 1)
        def _():
            @pl.when(t >= 2)
            def _():
                send_desc(slot).wait_send()
                pltpu.semaphore_wait(cap_sem, 1)
            send_buf[slot] = ship
"""


def _rdma_fixture(consume, guard=_GUARD):
    return _RDMA_TEMPLATE % {"consume": consume, "guard": guard}


GOOD_KERNEL = _rdma_fixture(_CONSUME_GOOD)
RACE1 = _rdma_fixture(_CONSUME_RACE1)
RACE2 = _rdma_fixture(_CONSUME_RACE2)
UNGUARDED = _rdma_fixture(
    _CONSUME_GOOD, guard="    del axis_name  # no ring-size guard\n")


def apx2(res, *, suppressed=False):
    return {f.rule for f in (res.suppressed() if suppressed
                             else res.unsuppressed())
            if f.rule.startswith("APX2")}


# ---------------------------------------------------------------------------
# the protocol micro-model-checker
# ---------------------------------------------------------------------------

class TestProtocolChecker:
    def test_good_kernel_clean(self):
        """The shipped protocol, verbatim as a fixture: no APX2xx
        findings at any ring size — the falsifiable negative for both
        race tests below."""
        res = run_lint(GOOD_KERNEL)
        assert not apx2(res), [f.render() for f in res.unsuppressed()]

    def test_race1_write_before_wait_flagged(self, monkeypatch):
        """PR 9 review round 1: the torn write is flagged AT ITS LINE
        (APX202) and the over-signalled credits as unpaired/undrained
        (APX201). Ring sizes capped at 4 here — the race first
        reproduces at n=4 and the un-flow-controlled fixture's n=5/6
        state spaces cost ~15s of tier-1 for no extra signal
        (test_kernel_rules_registered pins the default 1..6 sweep)."""
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3, 4))
        res = run_lint(RACE1)
        got = apx2(res)
        assert "APX202" in got and "APX201" in got, \
            [f.render() for f in res.unsuppressed()]
        wline = line_of(RACE1, "RACE1: write before the waits")
        torn = [f for f in res.unsuppressed() if f.rule == "APX202"
                and f.line == wline]
        assert torn, [f.render() for f in res.unsuppressed()]
        assert "still reading it" in torn[0].message

    def test_race2_signal_before_read_flagged(self):
        """PR 9 review round 2: the credit-before-read race is flagged
        at the read line as a schedule-dependent payload — and ONLY
        that (conservation and liveness are clean, exactly like the
        original bug)."""
        res = run_lint(RACE2)
        assert apx2(res) == {"APX202"}, \
            [f.render() for f in res.unsuppressed()]
        rline = line_of(RACE2, "RACE2: read after credit")
        bad = [f for f in res.unsuppressed() if f.rule == "APX202"]
        assert all(f.line == rline for f in bad)
        # ONE defect, one finding — ring sizes aggregate in the
        # message instead of multiplying near-identical findings
        assert len(bad) == 1, [f.render() for f in bad]
        # the race needs slot reuse: first reproducible ring size is 4
        assert "n=4,5,6" in bad[0].message

    def test_n1_hang_flagged_without_guard(self):
        """The n==1 never-started-DMA hang (PR 9 round 2): without a
        ring-size guard the kernel is flagged APX203 (hang) + APX204
        (missing guard)."""
        res = run_lint(UNGUARDED)
        got = apx2(res)
        assert "APX203" in got and "APX204" in got, \
            [f.render() for f in res.unsuppressed()]
        hang = [f for f in res.unsuppressed() if f.rule == "APX203"]
        assert any("n=1" in f.message for f in hang)

    def test_guard_licenses_n1_skip(self):
        """The falsifiable negative to the hang check: the SAME kernel
        with the `if n < 2: raise` guard loses both findings."""
        res = run_lint(GOOD_KERNEL)
        assert "APX203" not in codes(res)
        assert "APX204" not in codes(res)

    def test_nested_kernel_is_checked_not_its_wrapper(self):
        """Review fix: a protocol kernel DEFINED INSIDE its dispatch
        function must be the simulated subject — the wrapper (which
        `ast.walk` also sees the semaphore ops through) must get no
        bogus 'cannot be model-checked' finding, and a race in the
        nested kernel must still flag."""
        nested = HEADER + textwrap.dedent("""
        def dispatch(x, w, axis_name="tp"):
            n = jax.lax.axis_size(axis_name)
            if n < 2:
                raise ValueError("ring required")

            def _kern(x_ref, o_ref, acc_buf, send_sem, recv_sem, *,
                      n, axis_name):
                t = pl.program_id(0)
                d = pltpu.make_async_remote_copy(
                    acc_buf.at[0], acc_buf.at[0], send_sem.at[0],
                    recv_sem.at[0], device_id=1)

                @pl.when(t == 0)
                def _():
                    d.start()

                @pl.when(t == n - 1)
                def _():
                    o_ref[...] = acc_buf[0]   # read, but NO wait_recv
                    d.wait_send()

            return pl.pallas_call(
                functools.partial(_kern, n=n, axis_name=axis_name),
                grid=(n,))(x, w)
        """)
        res = run_lint(nested)
        msgs = [f for f in res.unsuppressed() if f.rule == "APX201"]
        assert not any("cannot be model-checked" in f.message
                       for f in msgs), [f.render() for f in msgs]
        # the un-waited recv_sem never drains; the unordered read races
        got = apx2(res)
        assert "APX201" in got, [f.render() for f in res.unsuppressed()]
        assert all("_kern" in f.message for f in msgs)

    def test_whole_ref_write_aliases_every_slot(self, monkeypatch):
        """Review fix: `send_buf[...] = ship` (whole-ref) must conflict
        with an in-flight DMA reading slot 1 — collapsing it to slot 0
        certified torn sends on slots 1+ as clean. The slot-indexed
        twin (GOOD_KERNEL) stays the falsifiable negative. Ring sizes
        capped at 4: the aliasing write de-flow-controls the fixture
        and the race already reproduces at n=3."""
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3, 4))
        aliased = GOOD_KERNEL.replace("send_buf[slot] = ship",
                                      "send_buf[...] = ship")
        res = run_lint(aliased)
        bad = [f for f in res.unsuppressed() if f.rule == "APX202"]
        assert any("still reading it" in f.message for f in bad), \
            [f.render() for f in res.unsuppressed()]

    def test_ordered_whole_ref_read_not_a_race(self):
        """Review fix: a whole-ref read AFTER both slots' recv waits is
        deterministic — per-slot payloads are distinct by design, and
        keying observations per slot must not read as a race."""
        src = HEADER + textwrap.dedent("""
        def _kern(x_ref, o_ref, sbuf, rbuf, send_sem, recv_sem, *, n,
                  axis_name):
            t = pl.program_id(0)

            def desc(s):
                return pltpu.make_async_remote_copy(
                    sbuf.at[s], rbuf.at[s], send_sem.at[s],
                    recv_sem.at[s], device_id=1)

            @pl.when(t == 0)
            def _():
                sbuf[0] = x_ref[...]
                sbuf[1] = x_ref[...]
                desc(0).start()
                desc(1).start()

            @pl.when(t == n - 1)
            def _():
                desc(0).wait_send()
                desc(1).wait_send()
                desc(0).wait_recv()
                desc(1).wait_recv()
                o_ref[...] = rbuf[...]

        def go(x, axis_name):
            n = jax.lax.axis_size(axis_name)
            if n < 2:
                raise ValueError
            return pl.pallas_call(
                functools.partial(_kern, n=n, axis_name=axis_name),
                grid=(n,))(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX202"]
        assert not bad, [f.render() for f in bad]

    def test_kwonly_default_helper_is_modelable(self):
        """Review fix: a kw-only default on an in-kernel helper must
        bind like a positional default, not fall out of the fragment."""
        src = HEADER + textwrap.dedent("""
        def _kern(x_ref, o_ref, send_sem, *, n, axis_name):
            t = pl.program_id(0)

            def sig(*, amount=1):
                pltpu.semaphore_signal(send_sem, inc=amount,
                                       device_id=1)

            @pl.when(t == 0)
            def _():
                sig()

            @pl.when(t == n - 1)
            def _():
                pltpu.semaphore_wait(send_sem, 1)

        def go(x, axis_name):
            n = jax.lax.axis_size(axis_name)
            if n < 2:
                raise ValueError
            return pl.pallas_call(
                functools.partial(_kern, n=n, axis_name=axis_name),
                grid=(n,))(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX201"
               and "cannot be model-checked" in f.message]
        assert not bad, [f.render() for f in bad]

    def test_unmodelable_kernel_flagged(self):
        src = """
            import functools
            import jax
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu
            import apex1_tpu

            def _kern(x_ref, o_ref, sem, *, n, axis_name):
                v = x_ref[...]

                @pl.when(v > 0)       # data-dependent predicate
                def _():
                    pltpu.semaphore_wait(sem, 1)

            def go(x, axis_name):
                n = jax.lax.axis_size(axis_name)
                if n < 2:
                    raise ValueError
                return pl.pallas_call(
                    functools.partial(_kern, n=n, axis_name=axis_name),
                    grid=(n,))(x)
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX201"]
        assert bad and "cannot be model-checked" in bad[0].message

    def test_apx2xx_suppression_grammar(self):
        """The APX1xx suppression grammar covers the new family:
        slug or code, reason mandatory."""
        marked = UNGUARDED.replace(
            "    return pl.pallas_call(",
            "    return pl.pallas_call(  # graftlint: allow(ring-guard)"
            " -- fixture: single-host smoke only")
        res = run_lint(marked)
        assert "APX204" not in codes(res)
        sup = [f for f in res.suppressed() if f.rule == "APX204"]
        assert sup and sup[0].reason.startswith("fixture:")

    def test_shipped_rdma_kernel_verifies_clean(self):
        """THE must-pass case: the real ops/fused_collective.py —
        protocol model-checked at n=2..6 (n==1 skipped: its dispatch
        is ring-size-guarded), mesh + budget passes included."""
        from apex1_tpu.lint import lint_files
        res = lint_files(
            [os.path.join(REPO, "apex1_tpu", "ops",
                          "fused_collective.py")],
            root=REPO, kernels=True)
        bad = [f for f in res.unsuppressed()
               if f.rule.startswith("APX2")]
        assert not bad, [f.render() for f in bad]


# ---------------------------------------------------------------------------
# mesh/collective consistency
# ---------------------------------------------------------------------------

class TestMeshRules:
    def test_ppermute_bijection_positive(self):
        src = """
            import jax

            def bad_ring(x, axis_name):
                n = jax.lax.axis_size(axis_name)
                perm = [(i, (i * 0) % n) for i in range(n)]
                return jax.lax.ppermute(x, axis_name, perm)
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX205"]
        assert bad and "duplicate destination" in bad[0].message

    def test_ppermute_ring_and_partial_clean(self):
        src = """
            import jax

            def ring(x, axis_name):
                n = jax.lax.axis_size(axis_name)
                perm = [(i, (i + 1) % n) for i in range(n)]
                return jax.lax.ppermute(x, axis_name, perm)

            def shift_no_wrap(x, axis_name):
                n = jax.lax.axis_size(axis_name)
                # partial permutations are legal (halo edge shifts)
                perm = [(i, i + 1) for i in range(n - 1)]
                return jax.lax.ppermute(x, axis_name, perm)
        """
        res = run_lint(src)
        assert "APX205" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_ppermute_out_of_range(self):
        src = """
            import jax

            def off_by_one(x, axis_name):
                n = jax.lax.axis_size(axis_name)
                perm = [(i, i + 1) for i in range(n)]   # dst == n
                return jax.lax.ppermute(x, axis_name, perm)
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX205"]
        assert bad and "outside" in bad[0].message

    def test_ppermute_unresolvable_is_skipped(self):
        src = """
            import jax

            def stages(x, axis_name, P):
                # P is a plain parameter, not the axis size: underclaim
                perm = [(i, (i + 1) % P) for i in range(P)]
                return jax.lax.ppermute(x, axis_name, perm)
        """
        assert "APX205" not in codes(run_lint(src))

    def test_axis_binding_positive_and_bound_literal(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def unbound(x):
                return jax.lax.psum(x, "nonexistent_axis")

            def bound(x):
                spec = P("tp")
                return jax.lax.psum(x, "tp")

            def contract(x, axis_name):
                return jax.lax.psum(x, axis_name)
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX206"]
        assert len(bad) == 1 and "nonexistent_axis" in bad[0].message

    def test_exclusive_knob_def_without_guard(self):
        src = """
            def layer(x, overlap=False, fused=False):
                if fused:
                    return x * 2
                if overlap:
                    return x * 3
                return x
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX207"]
        assert bad and "never raises" in bad[0].message

    def test_exclusive_knob_def_with_guard_clean(self):
        src = """
            def layer(x, overlap=False, fused=False):
                if overlap and fused:
                    raise ValueError("exclusive")
                return x
        """
        assert "APX207" not in codes(run_lint(src))

    def test_exclusive_knob_call_site(self):
        src = """
            def layer(x, overlap=False, fused=False):
                if overlap and fused:
                    raise ValueError("exclusive")
                return x

            def use(x, o):
                layer(x, overlap=True, fused=True)       # flagged
                layer(x, overlap=o, fused=False)         # fine
                layer(x, overlap=False, fused=True)      # fine
                layer(x, overlap=o, fused=True)          # fine: one
                #                         side is a runtime-guarded var
        """
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX207"]
        assert len(bad) == 1 and "mutually" in bad[0].message


# ---------------------------------------------------------------------------
# VMEM budget + kernel binding
# ---------------------------------------------------------------------------

_BUDGET_TEMPLATE = HEADER + """
def _k(x_ref, o_ref, acc):
    o_ref[...] = x_ref[...]

def go(x):
    return pl.pallas_call(
        _k,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((512, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((%(rows)s, 1024), jnp.float32)],
    )(x)
"""


class TestBudgetAndBinding:
    def test_vmem_over_budget_flagged(self):
        # 8192 x 1024 fp32 scratch = 32 MiB > the 16 MiB v5e budget
        res = run_lint(_BUDGET_TEMPLATE % {"rows": 8192})
        bad = [f for f in res.unsuppressed() if f.rule == "APX208"]
        assert bad and "planning budget" in bad[0].message

    def test_vmem_within_budget_clean(self):
        # the falsifiable negative: 512 x 1024 fp32 = 2 MiB fits
        res = run_lint(_BUDGET_TEMPLATE % {"rows": 512})
        assert "APX208" not in codes(res), \
            [f.render() for f in res.unsuppressed()]

    def test_arity_mismatch_flagged(self):
        src = HEADER + textwrap.dedent("""
            def _k(x_ref, o_ref):            # missing the scratch ref
                o_ref[...] = x_ref[...]

            def go(x):
                return pl.pallas_call(
                    _k,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
                )(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX209"]
        assert bad and "arity" in bad[0].message

    def test_index_map_arity_flagged(self):
        src = HEADER + textwrap.dedent("""
            def _k(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            def go(x):
                return pl.pallas_call(
                    _k,
                    grid=(4, 2),
                    in_specs=[pl.BlockSpec((8, 128),
                                           lambda i: (i, 0))],  # 1 != 2
                    out_specs=pl.BlockSpec((8, 128),
                                           lambda i, j: (i, j)),
                    out_shape=jax.ShapeDtypeStruct((32, 256),
                                                   jnp.float32),
                )(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX209"]
        assert bad and "index_map" in bad[0].message

    def test_semaphore_used_as_buffer_flagged(self):
        src = HEADER + textwrap.dedent("""
            def _k(x_ref, o_ref, sem):
                sem[0] = x_ref[...]          # writing a semaphore

            def go(x):
                return pl.pallas_call(
                    _k,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.SemaphoreType.REGULAR],
                )(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX209"]
        assert bad and "data buffer" in bad[0].message

    def test_partial_bound_params_not_counted(self):
        """Review fix: functools.partial-bound params (kw AND leading
        positional) are consumed before Pallas binds refs — a standard
        idiom, not an arity mismatch."""
        src = HEADER + textwrap.dedent("""
        def _k(scale, x_ref, o_ref, gain=1.0):
            o_ref[...] = x_ref[...]

        def go(x):
            return pl.pallas_call(
                functools.partial(_k, 2.0, gain=3.0),
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128),
                                               jnp.float32),
            )(x)
        """)
        res = run_lint(src)
        bad = [f for f in res.unsuppressed() if f.rule == "APX209"]
        assert not bad, [f.render() for f in bad]

    def test_clean_wiring_no_findings(self):
        src = HEADER + textwrap.dedent("""
            def _k(x_ref, o_ref, acc):
                acc[0] = x_ref[...]
                o_ref[...] = acc[0]

            def go(x):
                return pl.pallas_call(
                    _k,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                    scratch_shapes=[pltpu.VMEM((2, 128), jnp.float32)],
                )(x)
        """)
        res = run_lint(src)
        assert not apx2(res), [f.render() for f in res.unsuppressed()]


# ---------------------------------------------------------------------------
# the ONE VMEM sizing model (satellite: dedup pinned bit-identical)
# ---------------------------------------------------------------------------

# frozen PRE-REFACTOR copies of tuning/registry.py's formulas (PR 3-9
# in-module versions). The shared apex1_tpu.vmem_model must reproduce
# them bit-for-bit — edit these only with a conscious re-gating.
_L, _D = 128, 2


def _orig_flash(blocks, dims, es, budget):
    bq, bk = blocks["block_q"], blocks["block_k"]
    dp = dims["Dp"]
    est = (_D * es * (bq * dp + 2 * bk * dp) + _D * es * bq * dp
           + 4 * (bq * dp + 2 * bq * _L) + 2 * 4 * bq * bk)
    return est <= budget, est


def _orig_row(n_passes):
    def check(blocks, dims, _es, budget):
        br = blocks["block_rows"]
        est = n_passes * _D * br * dims["lanes"] * 4
        return est <= budget, est
    return check


def _orig_linear_xent(blocks, dims, es, budget):
    bt, bv = blocks["block_t"], blocks["block_v"]
    hp = dims["Hp"]
    acc = 4 * (bt + bv) * hp
    est = (acc + _D * es * (bt + bv) * hp + 2 * 4 * bt * bv)
    return est <= budget and acc <= (budget // 4) * 3 // 4, est


def _orig_cm(blocks, dims, es, budget):
    bm, bn = blocks["block_m"], blocks["block_n"]
    kp = dims["Kp"]
    est = _D * es * (bm * kp + kp * bn) + _D * 4 * bm * bn
    return est <= budget, est


def _orig_agf(blocks, dims, es, budget):
    ok, est = _orig_flash(blocks, dims, es, budget)
    bq, dp = blocks["block_q"], dims["Dp"]
    est += (_D * 4 * (bq * dp + bq * _L) + _D * 4 * bq * dp
            - _D * es * bq * dp)
    return est <= budget, est


def _orig_int8(blocks, dims, _es, budget):
    bn, bk = blocks["block_n"], blocks["block_k"]
    t = 1024
    est = (_D * (t * bk * 2 + bn * bk * 1 + bn * 4) + t * bn * 4)
    return est <= budget, est


# frozen as-landed copies of the PR 18 paged-decode formulas (no
# pre-refactor history — these pin the registry's gating against silent
# drift the same way; edit only with a conscious re-gating)
def _orig_paged(blocks, dims, es, budget):
    p = blocks["page_p"]
    dp, rq = dims["Dp"], dims["Rq"]
    est = (_D * es * 2 * p * dp + _D * 4 * rq * dp + _D * 4 * rq * dp
           + 4 * (rq * dp + 2 * rq * _L) + 2 * 4 * rq * p)
    return est <= budget, est


def _orig_fused_sample(blocks, dims, _es, budget):
    bv = blocks["block_v"]
    est = (_D * 4 * 8 * bv + 2 * _D * 4 * 8 * _L + 6 * 4 * 8 * bv)
    return est <= budget, est


# frozen as-landed copies of the PR 19 chunked-loss / fused-GLU / LoRA
# epilogue formulas (same no-silent-drift contract as _orig_paged)
def _orig_chunked_loss(blocks, dims, es, budget):
    cv = blocks["chunk_v"]
    hp = dims["Hp"]
    est = _D * 4 * 8 * cv + _D * es * 8 * hp + 4 * 8 * _L
    return est <= budget, est


def _orig_fused_swiglu(blocks, dims, es, budget):
    bt, bf = blocks["block_t"], blocks["block_f"]
    hp = dims["Hp"]
    est = (_D * es * (bt * hp + 2 * hp * bf) + _D * es * bt * bf
           + 2 * 4 * bt * bf)
    return est <= budget, est


def _orig_lora_epilogue(blocks, dims, es, budget):
    bv = blocks["block_v"]
    hp = dims["Hp"]
    est = (_D * es * 8 * hp + _D * es * 8 * bv + _D * es * 8 * hp
           + _D * es * 8 * bv + 4 * 8 * bv)
    return est <= budget, est


class TestVmemModelShared:
    _GRID = {
        "flash_attention": (_orig_flash,
                            [{"block_q": q, "block_k": k}
                             for q in (16, 128, 512)
                             for k in (16, 128, 512)],
                            [{"Dp": d, "Sb": 1024}
                             for d in (64, 128, 256)]),
        "fused_softmax": (_orig_row(3),
                          [{"block_rows": r}
                           for r in (8, 64, 512, 4096)],
                          [{"lanes": ln} for ln in (128, 512, 2048)]),
        "layer_norm": (_orig_row(5),
                       [{"block_rows": r} for r in (8, 512, 4096)],
                       [{"lanes": ln} for ln in (128, 2048)]),
        "rope": (_orig_row(6),
                 [{"block_rows": r} for r in (8, 512, 4096)],
                 [{"lanes": ln} for ln in (128, 2048)]),
        "xentropy": (_orig_row(2),
                     [{"block_rows": r} for r in (8, 512, 4096)],
                     [{"lanes": ln} for ln in (128, 2048)]),
        "bias_dropout_add": (_orig_row(4),
                             [{"block_rows": r} for r in (8, 4096)],
                             [{"lanes": ln} for ln in (128, 2048)]),
        "linear_xent": (_orig_linear_xent,
                        [{"block_t": t, "block_v": v}
                         for t in (16, 128, 512)
                         for v in (16, 256, 1024)],
                        [{"Hp": h} for h in (768, 4096)]),
        "fused_collective_matmul": (_orig_cm,
                                    [{"block_m": m, "block_n": n}
                                     for m in (16, 256, 1024)
                                     for n in (128, 512, 1024)],
                                    [{"Kp": k} for k in (128, 4096)]),
        "fused_ag_flash": (_orig_agf,
                           [{"block_q": q, "block_k": k}
                            for q in (16, 128, 512)
                            for k in (16, 512)],
                           [{"Dp": d, "Sb": 16384}
                            for d in (64, 128, 256)]),
        "int8_matmul": (_orig_int8,
                        [{"block_n": n, "block_k": k}
                         for n in (128, 256, 512)
                         for k in (128, 512, 1024)],
                        [{"N": 4096, "K": 4096}]),
        "paged_decode": (_orig_paged,
                         [{"page_p": p} for p in (8, 16, 64, 256, 2048)],
                         [{"Dp": d, "Rq": r}
                          for d in (128, 256)
                          for r in (8, 48, 512)]),
        "fused_sample": (_orig_fused_sample,
                         [{"block_v": v}
                          for v in (128, 1024, 25216, 50432, 1 << 20)],
                         [{"Vp": 50432}]),
        "chunked_loss": (_orig_chunked_loss,
                         [{"chunk_v": v}
                          for v in (128, 1024, 8192, 65536, 1 << 20)],
                         [{"Hp": h} for h in (128, 768, 4096, 8192)]),
        "fused_swiglu": (_orig_fused_swiglu,
                         [{"block_t": t, "block_f": f}
                          for t in (8, 128, 512)
                          for f in (128, 512, 2048)],
                         [{"Hp": h} for h in (128, 4096, 8192)]),
        "lora_epilogue": (_orig_lora_epilogue,
                          [{"block_v": v}
                           for v in (128, 2048, 50432, 1 << 20)],
                          [{"Hp": h, "Vp": 50432}
                           for h in (128, 4096, 8192)]),
    }

    def test_registry_gating_bit_identical(self):
        """THE dedup pin: every registry spec's check == the frozen
        pre-refactor formula, (ok, est) both, over a budget sweep that
        crosses every fits/doesn't boundary."""
        from apex1_tpu.tuning.registry import SPECS
        assert set(self._GRID) == set(SPECS)
        budgets = (2 * 2**20, 8 * 2**20, 16 * 2**20, 32 * 2**20)
        n_checked = 0
        for name, (orig, blocks_list, dims_list) in self._GRID.items():
            spec = SPECS[name]
            for blocks in blocks_list:
                for dims in dims_list:
                    for es in (1, 2, 4):
                        for budget in budgets:
                            assert spec.check(blocks, dims, es, budget) \
                                == orig(blocks, dims, es, budget), \
                                (name, blocks, dims, es, budget)
                            n_checked += 1
        assert n_checked > 1000   # the sweep is real, not vacuous

    def test_registry_checks_are_the_shared_objects(self):
        from apex1_tpu.tuning.registry import SPECS
        from apex1_tpu.vmem_model import CHECKS
        for name, spec in SPECS.items():
            assert spec.check is CHECKS[name], name

    def test_rdma_rule_reproduces_gate_data_points(self):
        """The previously comment-only 16*chunk*N rule, now falsifiable:
        the aot gate's passing shape fits v5e with margin, the measured
        RESOURCE_EXHAUSTED shape does not."""
        from apex1_tpu.vmem_model import (budget_bytes, rdma_check,
                                          rdma_slot_bytes)
        assert rdma_slot_bytes(256, 512) == 16 * 256 * 512
        v5e = budget_bytes("v5e")
        ok, est = rdma_check(256, 1024, 512, 2, v5e)
        assert ok and est < v5e // 2          # "fits with margin"
        over, est2 = rdma_check(512, 1024, 1024, 2, v5e)
        assert not over and est2 > v5e

    def test_rdma_dispatch_enforces_budget(self):
        """matmul_reduce_scatter_rdma consumes the shared rule live: an
        over-budget shape raises the sizing ValueError, not a Mosaic
        RESOURCE_EXHAUSTED on silicon. (Checked through the sizing
        logic — off-TPU the entry raises NotImplementedError first, so
        drive the formula the dispatch calls.)"""
        from apex1_tpu.ops import fused_collective
        import inspect
        src = inspect.getsource(
            fused_collective.matmul_reduce_scatter_rdma)
        assert "rdma_check" in src and "raise ValueError" in src


# ---------------------------------------------------------------------------
# repo-wide self-check + CLI
# ---------------------------------------------------------------------------

class TestRepoKernelSelfCheck:
    def test_repo_kernels_clean(self):
        """The dogfood gate: the whole repo passes the APX2xx analyzer
        (every pallas_call site, the full shard_map surface), with any
        suppression carrying a reason."""
        res = lint_paths(["apex1_tpu", "tools", "examples"],
                         root=REPO, kernels=True)
        bad = res.unsuppressed()
        assert not bad, "unsuppressed findings:\n" + \
            "\n".join(f.render() for f in bad)
        for f in res.suppressed():
            assert f.reason and f.reason.strip(), f.render()

    def test_analyzer_actually_covers_the_repo(self):
        """Guard against a silently no-op analyzer: the site extractor
        must see the repo's pallas_call population and the protocol
        pass must model the RDMA kernel."""
        from apex1_tpu.lint import (collect_files, lint_files,
                                    module_name_for)
        from apex1_tpu.lint.project import build_project
        from apex1_tpu.lint.kernels.extract import (is_protocol_kernel,
                                                    pallas_sites)
        files = collect_files(["apex1_tpu"], root=REPO)
        named = {}
        for f in files:
            rel = os.path.relpath(f, REPO)
            named[rel] = (module_name_for(f, REPO),
                          open(f, encoding="utf-8").read())
        project = build_project(named)
        sites = pallas_sites(project)
        assert len(sites) >= 20, len(sites)
        protocol = [i for i in project.functions.values()
                    if is_protocol_kernel(project, i)
                    and i.name == "_mrs_rdma_kernel"]
        assert protocol, "the RDMA kernel fell out of the protocol scan"
        with_kernel = [s for s in sites if s.kernel is not None]
        assert len(with_kernel) >= 15, len(with_kernel)

    def test_kernel_rules_registered(self):
        from apex1_tpu.lint.kernels import KERNEL_RULES, RING_SIZES
        from apex1_tpu.lint.core import RULE_SLUGS
        assert [r.code for r in KERNEL_RULES] == [
            "APX201", "APX202", "APX203", "APX204", "APX205",
            "APX206", "APX207", "APX208", "APX209"]
        for r in KERNEL_RULES:
            assert RULE_SLUGS[r.code] == r.slug
        # the default sweep is the full 1..6 contract (the race tests
        # above cap it locally for wall-time only)
        assert RING_SIZES == (1, 2, 3, 4, 5, 6)

    def test_baseline_banked_with_kernel_family(self):
        path = os.path.join(REPO, "perf_results", "lint_baseline.json")
        doc = json.load(open(path))
        assert doc["ok"] is True
        assert doc["counts"]["unsuppressed"] == 0
        assert "APX201" in doc["rules"], \
            "re-bank with `python tools/lint.py --kernels --json`"


class TestCliKernels:
    def _run(self, *args, env_extra=None):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               **(env_extra or {})}
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint.py"),
             *args],
            capture_output=True, text=True, cwd=REPO, env=env)

    def test_kernels_flag_finds_fixture_races(self, tmp_path):
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "race.py").write_text(RACE2)
        p = self._run("--kernels", str(d))
        assert p.returncode == 1, p.stdout + p.stderr
        assert "APX202" in p.stdout

    def test_kernels_flag_clean_without_fixture(self, tmp_path):
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "ok.py").write_text(GOOD_KERNEL)
        p = self._run("--kernels", str(d))
        assert p.returncode == 0, p.stdout + p.stderr

    def test_list_rules_includes_family(self):
        p = self._run("--list-rules")
        assert p.returncode == 0
        for code in ("APX201", "APX205", "APX208"):
            assert code in p.stdout

    def test_cli_kernels_path_is_jax_free(self, tmp_path):
        """The check_all step's cold-start contract: the --kernels CLI
        never imports jax (stub parents for apex1_tpu and
        apex1_tpu.core). Poison jax on the path — the analyzer must
        still run and still find the fixture race."""
        poison = tmp_path / "site"
        poison.mkdir()
        (poison / "jax.py").write_text(
            "raise ImportError('poisoned: the lint CLI must stay "
            "jax-free')\n")
        d = tmp_path / "apex1_tpu"
        d.mkdir()
        (d / "race.py").write_text(RACE2)
        p = self._run(
            "--kernels", str(d),
            env_extra={"PYTHONPATH": str(poison)})
        assert p.returncode == 1, p.stdout + p.stderr
        assert "poisoned" not in p.stderr
        assert "APX202" in p.stdout


# ---------------------------------------------------------------------------
# paged-decode block-table publish: the file-based golden/bug pair
# ---------------------------------------------------------------------------

FIXDIR = os.path.join(REPO, "tests", "fixtures", "kernels")


def _load_fixture(name):
    with open(os.path.join(FIXDIR, name)) as fh:
        return fh.read()


class TestPagedBtPublishFixtures:
    """ISSUE 18's protocol pair: the double-buffered block-table
    publish loop behind the paged KV pool, as on-disk fixtures under
    tests/fixtures/kernels/ (the golden and bug halves diff as ONE
    moved statement). Ring sizes are capped at 3-4: local-DMA devices
    never interact, so n=5/6 multiply per-device delivery timings into
    the state cap without adding schedules (the torn read first
    reproduces at n=3)."""

    def test_golden_publish_clean(self, monkeypatch):
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3, 4))
        src = _load_fixture("paged_bt_publish_golden.py")
        res = run_lint(src)
        assert not apx2(res), [f.render() for f in res.unsuppressed()]

    def test_torn_block_table_read_flagged(self, monkeypatch):
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3))
        src = _load_fixture("paged_bt_publish_torn_bt_bug.py")
        res = run_lint(src)
        assert apx2(res) == {"APX202"}, \
            [f.render() for f in res.unsuppressed()]
        wline = line_of(src, "BUG: torn block-table read")
        torn = [f for f in res.unsuppressed() if f.rule == "APX202"]
        assert len(torn) == 1, [f.render() for f in torn]
        assert torn[0].line == wline
        assert "still reading it" in torn[0].message

    def test_pair_differs_by_one_moved_statement(self):
        """The pair's contract: identical protocols modulo the write
        placement — so the flagged defect IS the moved line, not an
        unrelated drift between the files."""
        def code_lines(name):
            body = _load_fixture(name).split('"""', 2)[2]
            lines = [ln.split("#")[0].rstrip()
                     for ln in body.splitlines()]
            return [ln for ln in lines if ln.strip()]

        g = code_lines("paged_bt_publish_golden.py")
        b = code_lines("paged_bt_publish_torn_bt_bug.py")
        assert sorted(g) == sorted(b)
        assert g != b


class TestLoraPagePublishFixtures:
    """ISSUE 19's protocol pair: the double-buffered adapter-page
    publish loop behind the multi-tenant LoRA store
    (serving.lora.LoraAdapterStore.register phase 1), as on-disk
    fixtures under tests/fixtures/kernels/. Same race class as the
    block-table pair — a staging slot rewritten while the publish DMA
    from two steps ago is still reading it — but the torn payload here
    is adapter weights, not page indices: a decode step whose LoRA
    block-table row already names the page gathers a half-updated
    adapter. The golden/bug halves diff as ONE moved statement."""

    def test_golden_publish_clean(self, monkeypatch):
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3, 4))
        src = _load_fixture("lora_page_publish_golden.py")
        res = run_lint(src)
        assert not apx2(res), [f.render() for f in res.unsuppressed()]

    def test_torn_page_publish_flagged(self, monkeypatch):
        import apex1_tpu.lint.kernels as K
        monkeypatch.setattr(K, "RING_SIZES", (1, 2, 3))
        src = _load_fixture("lora_page_publish_torn_page_bug.py")
        res = run_lint(src)
        assert apx2(res) == {"APX202"}, \
            [f.render() for f in res.unsuppressed()]
        wline = line_of(src, "BUG: torn adapter-page publish")
        torn = [f for f in res.unsuppressed() if f.rule == "APX202"]
        assert len(torn) == 1, [f.render() for f in torn]
        assert torn[0].line == wline
        assert "still reading it" in torn[0].message

    def test_pair_differs_by_one_moved_statement(self):
        """The pair's contract: identical protocols modulo the write
        placement — so the flagged defect IS the moved line, not an
        unrelated drift between the files."""
        def code_lines(name):
            body = _load_fixture(name).split('"""', 2)[2]
            lines = [ln.split("#")[0].rstrip()
                     for ln in body.splitlines()]
            return [ln for ln in lines if ln.strip()]

        g = code_lines("lora_page_publish_golden.py")
        b = code_lines("lora_page_publish_torn_page_bug.py")
        assert sorted(g) == sorted(b)
        assert g != b
