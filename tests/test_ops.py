"""Kernel parity tests — ≙ ``tests/L0/run_fused_layer_norm``,
``apex/contrib/test/{xentropy,layer_norm,multihead_attn}``: each Pallas
kernel (interpret mode on CPU) vs the pure-jnp gold, fwd values AND grads,
at fp32/bf16 tolerances."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu import ops
from apex1_tpu.ops import _common

FP32_TOL = dict(rtol=1e-5, atol=1e-5)
BF16_TOL = dict(rtol=2e-2, atol=2e-2)


def check_fwd_bwd(fn_pallas, fn_gold, args, diff_argnums=(0,), tol=FP32_TOL):
    """Compare primal and grads (summed-output scalar) across impls."""
    out_p = fn_pallas(*args)
    out_g = fn_gold(*args)
    np.testing.assert_allclose(np.asarray(out_p, np.float32),
                               np.asarray(out_g, np.float32), **tol)

    def scalar_p(*a):
        return jnp.sum(fn_pallas(*a).astype(jnp.float32) ** 2)

    def scalar_g(*a):
        return jnp.sum(fn_gold(*a).astype(jnp.float32) ** 2)

    gp = jax.grad(scalar_p, argnums=diff_argnums)(*args)
    gg = jax.grad(scalar_g, argnums=diff_argnums)(*args)
    for a, b in zip(gp, gg):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol)


class TestLayerNorm:
    @pytest.mark.parametrize("shape", [(4, 8, 256), (3, 384), (16, 130)])
    def test_parity_fp32(self, rng, shape):
        x = jnp.asarray(rng.normal(size=shape), jnp.float32)
        g = jnp.asarray(rng.normal(size=shape[-1:]) + 1.0, jnp.float32)
        b = jnp.asarray(rng.normal(size=shape[-1:]), jnp.float32)

        def pallas_fn(x, g, b):
            with _common.force_impl("pallas"):
                return ops.layer_norm(x, g, b)

        def gold_fn(x, g, b):
            with _common.force_impl("xla"):
                return ops.layer_norm(x, g, b)

        check_fwd_bwd(pallas_fn, gold_fn, (x, g, b), diff_argnums=(0, 1, 2))

    def test_mixed_dtype_bf16(self, rng):
        x = jnp.asarray(rng.normal(size=(6, 256)), jnp.bfloat16)
        g = jnp.asarray(rng.normal(size=(256,)) + 1.0, jnp.float32)
        b = jnp.zeros((256,), jnp.float32)
        with _common.force_impl("pallas"):
            y = ops.layer_norm(x, g, b)
        assert y.dtype == jnp.bfloat16
        with _common.force_impl("xla"):
            y_gold = ops.layer_norm(x, g, b)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(y_gold, np.float32), **BF16_TOL)

    def test_normalization_property(self, rng):
        # unit-affine LN output has ~zero mean, ~unit var per row
        x = jnp.asarray(rng.normal(size=(4, 512)) * 7 + 3, jnp.float32)
        with _common.force_impl("pallas"):
            y = ops.layer_norm(x, jnp.ones(512), jnp.zeros(512))
        np.testing.assert_allclose(np.asarray(jnp.mean(y, -1)), 0.0,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(jnp.var(y, -1)), 1.0,
                                   rtol=1e-3)


class TestRMSNorm:
    @pytest.mark.parametrize("shape", [(4, 256), (2, 5, 384)])
    def test_parity(self, rng, shape):
        x = jnp.asarray(rng.normal(size=shape), jnp.float32)
        g = jnp.asarray(rng.normal(size=shape[-1:]) + 1.0, jnp.float32)

        def pallas_fn(x, g):
            with _common.force_impl("pallas"):
                return ops.rms_norm(x, g)

        def gold_fn(x, g):
            with _common.force_impl("xla"):
                return ops.rms_norm(x, g)

        check_fwd_bwd(pallas_fn, gold_fn, (x, g), diff_argnums=(0, 1))

    def test_module(self, rng):
        m = ops.FusedRMSNorm(256)
        x = jnp.asarray(rng.normal(size=(3, 256)), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == x.shape


class TestSoftmax:
    def test_causal_parity(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 4, 16, 16)), jnp.float32)

        def pallas_fn(x):
            with _common.force_impl("pallas"):
                return ops.scaled_upper_triang_masked_softmax(x, scale=0.5)

        def gold_fn(x):
            with _common.force_impl("xla"):
                return ops.scaled_upper_triang_masked_softmax(x, scale=0.5)

        check_fwd_bwd(pallas_fn, gold_fn, (x,))
        # causal property: strictly-upper entries are 0
        y = pallas_fn(x)
        up = np.triu(np.ones((16, 16)), k=1).astype(bool)
        assert np.all(np.asarray(y)[..., up] < 1e-7)

    def test_masked_parity(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 2, 8, 24)), jnp.float32)
        mask = jnp.where(
            jnp.asarray(rng.random((2, 1, 8, 24)) < 0.3), ops.NEG_INF, 0.0)

        def pallas_fn(x, m):
            with _common.force_impl("pallas"):
                return ops.scaled_masked_softmax(x, m, scale=2.0)

        def gold_fn(x, m):
            with _common.force_impl("xla"):
                return ops.scaled_masked_softmax(x, m, scale=2.0)

        check_fwd_bwd(pallas_fn, gold_fn, (x, mask))

    def test_broadcast_key_mask(self, rng):
        """A mask whose KEY dim is size 1 must broadcast in-kernel (lane
        padding would silently unmask keys 1..Sk-1)."""
        x = jnp.asarray(rng.normal(size=(2, 2, 8, 24)), jnp.float32)
        mask = jnp.where(
            jnp.asarray(rng.random((2, 1, 8, 1)) < 0.5), ops.NEG_INF, 0.0)

        def pallas_fn(x, m):
            with _common.force_impl("pallas"):
                return ops.scaled_masked_softmax(x, m, scale=1.5)

        def gold_fn(x, m):
            with _common.force_impl("xla"):
                return ops.scaled_masked_softmax(x, m, scale=1.5)

        check_fwd_bwd(pallas_fn, gold_fn, (x, mask))

    def test_single_row_inputs(self, rng):
        """Decode-path shapes (sq=1, single rows) parity — the adaptive
        block clamp must not pad tiny inputs up to dead work, and the
        results must still match the gold."""
        from apex1_tpu.ops._common import row_block
        assert row_block(128, rows=1) == 8
        x = jnp.asarray(rng.normal(size=(2, 4, 1, 128)), jnp.float32)
        with _common.force_impl("pallas"):
            got = ops.scaled_masked_softmax(x, None, scale=1.0)
        with _common.force_impl("xla"):
            want = ops.scaled_masked_softmax(x, None, scale=1.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        z = jnp.asarray(rng.normal(size=(1, 1024)), jnp.float32)
        g = jnp.ones((1024,), jnp.float32)
        with _common.force_impl("pallas"):
            got = ops.layer_norm(z, g, jnp.zeros_like(g))
        with _common.force_impl("xla"):
            want = ops.layer_norm(z, g, jnp.zeros_like(g))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_rows_sum_to_one(self, rng):
        x = jnp.asarray(rng.normal(size=(3, 2, 8, 40)), jnp.float32)
        with _common.force_impl("pallas"):
            y = ops.scaled_masked_softmax(x, None, scale=1.0)
        np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), 1.0,
                                   rtol=1e-5)

    def test_adapter(self, rng):
        x = jnp.asarray(rng.normal(size=(1, 2, 8, 8)), jnp.float32)
        sm = ops.FusedScaleMaskSoftmax(attn_mask_type="causal", scale=1.0)
        y = sm(x)
        assert y.shape == x.shape


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_parity(self, rng, smoothing):
        V = 307  # non-multiple of 128 exercises padding
        logits = jnp.asarray(rng.normal(size=(10, V)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, size=(10,)), jnp.int32)

        def pallas_fn(lg):
            with _common.force_impl("pallas"):
                return ops.softmax_cross_entropy_loss(
                    lg, labels, smoothing=smoothing)

        def gold_fn(lg):
            with _common.force_impl("xla"):
                return ops.softmax_cross_entropy_loss(
                    lg, labels, smoothing=smoothing)

        check_fwd_bwd(pallas_fn, gold_fn, (logits,))

    def test_padding_idx(self, rng):
        V = 128
        logits = jnp.asarray(rng.normal(size=(6, V)), jnp.float32)
        labels = jnp.asarray([1, 2, 0, 3, 0, 5], jnp.int32)

        def loss_sum(lg):
            with _common.force_impl("pallas"):
                return jnp.sum(ops.softmax_cross_entropy_loss(
                    lg, labels, padding_idx=0))

        loss = ops.softmax_cross_entropy_loss(logits, labels, padding_idx=0)
        assert float(loss[2]) == 0.0 and float(loss[4]) == 0.0
        g = jax.grad(loss_sum)(logits)
        np.testing.assert_allclose(np.asarray(g[2]), 0.0, atol=1e-7)
        assert np.abs(np.asarray(g[0])).max() > 0

    def test_vs_manual_ce(self, rng):
        # plain CE (no smoothing) vs -log_softmax[target]
        V = 256
        logits = jnp.asarray(rng.normal(size=(8, V)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, size=(8,)), jnp.int32)
        with _common.force_impl("pallas"):
            loss = ops.softmax_cross_entropy_loss(logits, labels)
        manual = -jax.nn.log_softmax(logits)[jnp.arange(8), labels]
        np.testing.assert_allclose(np.asarray(loss), np.asarray(manual),
                                   rtol=1e-5, atol=1e-5)


class TestRoPE:
    @pytest.mark.parametrize("interleaved", [False, True])
    def test_parity(self, rng, interleaved):
        B, S, H, D = 2, 16, 4, 256  # half=128 → pallas path
        x = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        cos, sin = ops.rope_tables(jnp.arange(S), D)

        def pallas_fn(x):
            with _common.force_impl("pallas"):
                return ops.apply_rotary_pos_emb(x, cos, sin,
                                                interleaved=interleaved)

        def gold_fn(x):
            with _common.force_impl("xla"):
                return ops.apply_rotary_pos_emb(x, cos, sin,
                                                interleaved=interleaved)

        check_fwd_bwd(pallas_fn, gold_fn, (x,))

    def test_norm_preserved(self, rng):
        # rotations preserve the norm of each (x1,x2) pair
        B, S, D = 1, 8, 64
        x = jnp.asarray(rng.normal(size=(B, S, D)), jnp.float32)
        cos, sin = ops.rope_tables(jnp.arange(S), D)
        y = ops.apply_rotary_pos_emb(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)

    def test_grad_is_inverse_rotation(self, rng):
        S, D = 4, 32
        x = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
        cos, sin = ops.rope_tables(jnp.arange(S), D)
        # d/dx sum(rope(x) * t) == rope^T(t) == rope with -sin
        t = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
        g = jax.grad(lambda x: jnp.sum(ops.apply_rotary_pos_emb(
            x, cos, sin) * t))(x)
        expected = ops.apply_rotary_pos_emb(t, cos, -sin)
        np.testing.assert_allclose(np.asarray(g), np.asarray(expected),
                                   rtol=1e-5, atol=1e-6)


def test_xentropy_num_classes_padded_vocab(rng):
    """Lane-padded vocab logits with num_classes masking == sliced logits
    (Megatron-style padded LM head, no slice copy)."""
    from apex1_tpu.ops import force_impl, softmax_cross_entropy_loss
    logits = jnp.asarray(rng.normal(size=(6, 256)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 200, (6,)), jnp.int32)
    for impl in ("pallas", "xla"):
        with force_impl(impl):
            got = softmax_cross_entropy_loss(logits, labels,
                                             num_classes=200)
            want = softmax_cross_entropy_loss(logits[:, :200], labels)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-6, err_msg=impl)
            g = jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(
                l, labels, num_classes=200)))(logits)
            np.testing.assert_array_equal(np.asarray(g[:, 200:]), 0.0)
            gw = jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(
                l, labels)))(logits[:, :200])
            np.testing.assert_allclose(np.asarray(g[:, :200]),
                                       np.asarray(gw), rtol=1e-5,
                                       atol=1e-6, err_msg=impl)
