"""Tests for the shape-keyed kernel autotuner (`apex1_tpu.tuning`).

Covers the acceptance surface of the tuning layer:

- table lookup / miss / fallback to the analytic heuristics;
- key normalization (padded dims, dtype spellings, capability
  generation scoping);
- VMEM-budget validity: over-budget or misaligned entries are rejected
  at lookup AND flagged by the strict `validate_tables` gate;
- round-trip persistence (record -> save -> reload -> lookup);
- the EMPTY-TABLE bit-for-bit pin: with no tables, every op resolves
  exactly the legacy heuristic blocks (the "today's choices" contract);
- precedence: explicit arg > APEX1_ATTN_BLOCK_* env > table > heuristic;
- the trace-counter proof that an in-process two-candidate sweep
  compiles exactly two executables with no jit-cache
  cross-contamination (the property that makes `tools/tune_kernels.py`
  fit a hardware window);
- the sweep driver itself on the CPU backend (interpret-mode plumbing).
"""

import functools
import importlib.util
import json
import os
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu import tuning
from apex1_tpu.ops._common import force_impl, row_block

_REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture()
def tables_dir(tmp_path, monkeypatch):
    """Point the tuning layer at an isolated (initially EMPTY) dir."""
    monkeypatch.setenv("APEX1_TUNING_DIR", str(tmp_path))
    tuning.clear_cache()
    yield tmp_path
    tuning.clear_cache()


# --------------------------------------------------------------------------
# table core: lookup / miss / persistence / keys
# --------------------------------------------------------------------------

class TestTable:
    def test_miss_on_empty_dir(self, tables_dir):
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             "bfloat16") is None

    def test_record_lookup_roundtrip_persistence(self, tables_dir):
        blocks = {"block_q": 256, "block_k": 512}
        key, entry = tuning.record("flash_attention", {"Dp": 128},
                                   jnp.bfloat16, blocks, time_ms=1.25)
        assert key == "v5e|bfloat16|Dp=128"
        assert entry["timing"] == "interpret"  # swept off-TPU
        # in-memory visibility before any save
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             "bfloat16") == blocks
        path = tuning.save("flash_attention")
        tuning.clear_cache()  # force a reload from disk
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             jnp.bfloat16) == blocks
        doc = json.loads(pathlib.Path(path).read_text())
        assert doc["schema"] == 1 and doc["kernel"] == "flash_attention"
        assert doc["entries"][key]["blocks"] == blocks

    def test_save_merges_with_entries_on_disk(self, tables_dir):
        tuning.record("layer_norm", {"lanes": 768}, "bfloat16",
                      {"block_rows": 128})
        tuning.save("layer_norm")
        tuning.clear_cache()
        tuning.record("layer_norm", {"lanes": 2048}, "bfloat16",
                      {"block_rows": 64})
        tuning.save("layer_norm")
        tuning.clear_cache()
        assert tuning.lookup("layer_norm", {"lanes": 768},
                             "bfloat16") == {"block_rows": 128}
        assert tuning.lookup("layer_norm", {"lanes": 2048},
                             "bfloat16") == {"block_rows": 64}

    def test_key_normalization(self, tables_dir):
        # dims sorted by name; dtype spellings canonicalized; off-TPU
        # generation defaults to the v5e planning row
        k1 = tuning.make_key({"N": 2048, "K": 1024}, "int8")
        assert k1 == "v5e|int8|K=1024,N=2048"
        assert tuning.make_key({"Dp": 128}, jnp.bfloat16) == \
            tuning.make_key({"Dp": 128}, np.dtype("bfloat16")) == \
            tuning.make_key({"Dp": 128}, "bfloat16")
        # round trip
        gen, dt, dims = tuning.parse_key(k1)
        assert (gen, dt, dims) == ("v5e", "int8",
                                   {"K": 1024, "N": 2048})
        # different dtype / dims / generation -> different keys
        assert tuning.make_key({"Dp": 128}, "float32") != \
            tuning.make_key({"Dp": 128}, "bfloat16")
        assert tuning.make_key({"Dp": 256}, "bfloat16") != \
            tuning.make_key({"Dp": 128}, "bfloat16")
        assert tuning.make_key({"Dp": 128}, "bfloat16", "v5p") != \
            tuning.make_key({"Dp": 128}, "bfloat16")

    def test_generation_scoping(self, tables_dir):
        tuning.record("flash_attention", {"Dp": 128}, "bfloat16",
                      {"block_q": 1024, "block_k": 512},
                      generation="v5p")
        # v5p winner must not leak to the (default) v5e lookup
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             "bfloat16") is None
        assert tuning.lookup("flash_attention", {"Dp": 128}, "bfloat16",
                             generation="v5p") == \
            {"block_q": 1024, "block_k": 512}

    def test_corrupt_file_is_a_miss_not_a_crash(self, tables_dir):
        (tables_dir / "flash_attention.json").write_text("{not json")
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             "bfloat16") is None
        assert any("flash_attention" in p for p in tuning.load_problems())


# --------------------------------------------------------------------------
# VMEM-budget validity
# --------------------------------------------------------------------------

class TestVmemValidity:
    def test_over_budget_entry_rejected_at_lookup(self, tables_dir):
        # (4096, 4096) fp32 score tiles alone are ~128 MiB — far over
        # any generation's budget; the entry must be a miss and the op
        # must fall back to the heuristic
        tuning.record("flash_attention", {"Dp": 128, "Sb": 128},
                      "bfloat16", {"block_q": 4096, "block_k": 4096})
        assert tuning.lookup("flash_attention", {"Dp": 128, "Sb": 128},
                             "bfloat16") is None
        from apex1_tpu.ops.attention import _auto_blocks
        assert _auto_blocks(64, None, None, jnp.bfloat16) == (512, 512)

    def test_linear_xent_accumulator_bound(self, tables_dir):
        # the AOT-established bound: fp32 dx+dw accumulators must fit
        # 3/4 of a quarter of VMEM — (512, 1024) at Hp=768 exceeds it
        tuning.record("linear_xent", {"Hp": 768}, "bfloat16",
                      {"block_t": 512, "block_v": 1024})
        assert tuning.lookup("linear_xent", {"Hp": 768},
                             "bfloat16") is None
        tuning.record("linear_xent", {"Hp": 768}, "bfloat16",
                      {"block_t": 512, "block_v": 512})
        assert tuning.lookup("linear_xent", {"Hp": 768}, "bfloat16") == \
            {"block_t": 512, "block_v": 512}

    def test_misaligned_blocks_rejected(self, tables_dir):
        tuning.record("flash_attention", {"Dp": 128}, "bfloat16",
                      {"block_q": 100, "block_k": 512})  # 100 % 16 != 0
        assert tuning.lookup("flash_attention", {"Dp": 128},
                             "bfloat16") is None

    def test_validate_tables_flags_bad_entries(self, tables_dir):
        # over-budget entry, written to disk
        tuning.record("flash_attention", {"Dp": 128, "Sb": 4096},
                      "bfloat16", {"block_q": 4096, "block_k": 4096})
        tuning.save("flash_attention")
        # unknown kernel file + corrupt file + bad key
        (tables_dir / "warp_speed.json").write_text(
            '{"schema": 1, "kernel": "warp_speed", "entries": {}}')
        (tables_dir / "layer_norm.json").write_text("{not json")
        (tables_dir / "rope.json").write_text(json.dumps(
            {"schema": 1, "kernel": "rope",
             "entries": {"garbage-key": {"blocks": {"block_rows": 64}}}}))
        problems = tuning.validate_tables(str(tables_dir))
        assert len(problems) == 4
        joined = "\n".join(problems)
        for frag in ("flash_attention", "warp_speed", "layer_norm",
                     "rope"):
            assert frag in joined

    def test_validate_tables_clean(self, tables_dir):
        tuning.record("xentropy", {"lanes": 50432}, "float32",
                      {"block_rows": 8})
        tuning.save("xentropy")
        assert tuning.validate_tables(str(tables_dir)) == []
        assert tuning.validate_tables(str(tables_dir / "nope")) == []


# --------------------------------------------------------------------------
# empty-table bit-for-bit pins + precedence
# --------------------------------------------------------------------------

class TestResolution:
    def test_empty_table_reproduces_heuristics(self, tables_dir):
        """With NO tables, every op's resolver must return exactly the
        legacy analytic choices (the acceptance pin)."""
        from apex1_tpu.ops import attention, linear_xent, quantized

        # flash attention: 512x512 default; 256 at Dp > 512
        assert attention._auto_blocks(64, None, None) == (512, 512)
        assert attention._auto_blocks(128, None, None) == (512, 512)
        assert attention._auto_blocks(640, None, None) == (256, 256)
        # row kernels delegate to ops._common.row_block unchanged
        for lanes, rows in ((768, 8192), (1024, 1024), (50432, 8184),
                            (128, 32)):
            for kern in ("fused_softmax", "layer_norm", "rope",
                         "xentropy"):
                assert tuning.tuned_row_block(kern, lanes, rows=rows) \
                    == row_block(lanes, rows=rows)
        # pin the absolute values too (heuristic drift would silently
        # retarget every kernel)
        assert row_block(1024, rows=1024) == 256
        assert row_block(50432, rows=8184) == 8
        # fused LM-head CE
        assert linear_xent._auto_blocks(768, None, None) == (256, 512)
        assert linear_xent._auto_blocks(4096, None, None) == (64, 128)
        # int8 decode GEMM
        assert quantized._resolve_blocks(2048, 2048, None, None) == \
            (256, 512)

    def test_table_feeds_attention_and_linear_xent(self, tables_dir):
        from apex1_tpu.ops import attention, linear_xent

        tuning.record("flash_attention", {"Dp": 128, "Sb": 128},
                      "bfloat16", {"block_q": 256, "block_k": 128})
        tuning.record("linear_xent", {"Hp": 768}, "bfloat16",
                      {"block_t": 128, "block_v": 256})
        assert attention._auto_blocks(64, None, None, jnp.bfloat16) == \
            (256, 128)
        # dtype scoping: fp32 lookups miss the bf16 entry
        assert attention._auto_blocks(64, None, None, jnp.float32) == \
            (512, 512)
        # SEQ scoping: the 128-bucket winner must not govern other
        # buckets (a 1k winner never silently drives a 16k program)
        assert attention._auto_blocks(64, None, None, jnp.bfloat16,
                                      seq=16384) == (512, 512)
        assert tuning.seq_bucket(16384) == 16384
        assert tuning.seq_bucket(1025) == 2048
        assert tuning.seq_bucket(64) == 128
        assert linear_xent._auto_blocks(768, None, None, jnp.bfloat16) \
            == (128, 256)
        # explicit args always win
        assert attention._auto_blocks(64, 512, None, jnp.bfloat16) == \
            (512, 128)

    def test_env_beats_table_explicit_beats_env(self, tables_dir,
                                                monkeypatch):
        from apex1_tpu.ops import attention

        tuning.record("flash_attention", {"Dp": 128, "Sb": 128},
                      "bfloat16", {"block_q": 256, "block_k": 256})
        monkeypatch.setenv("APEX1_ATTN_BLOCK_Q", "128")
        assert attention._auto_blocks(64, None, None, jnp.bfloat16) == \
            (128, 256)   # env wins q; table still fills k
        assert attention._auto_blocks(64, 512, None, jnp.bfloat16) == \
            (512, 256)   # explicit beats env
        monkeypatch.setenv("APEX1_ATTN_BLOCK_Q", "100")
        with pytest.raises(ValueError, match="multiple of 16"):
            attention._auto_blocks(64, None, None, jnp.bfloat16)


    def test_explicit_blocks_immune_to_malformed_env(self, tables_dir,
                                                     monkeypatch):
        # a stale/typoed pin must not break explicit-block callers (the
        # sweep driver passes explicit candidates)
        from apex1_tpu.ops import attention

        monkeypatch.setenv("APEX1_ATTN_BLOCK_Q", "not-a-number")
        monkeypatch.setenv("APEX1_ATTN_BLOCK_K", "100")
        assert attention._auto_blocks(64, 256, 128, jnp.bfloat16) == \
            (256, 128)
        with pytest.raises(ValueError):
            attention._auto_blocks(64, None, 128, jnp.bfloat16)

    def test_tuned_row_block_clamps_to_rows(self, tables_dir):
        tuning.record("layer_norm", {"lanes": 768}, "bfloat16",
                      {"block_rows": 512})
        # production-scale winner must not pad a 20-row input to 512
        assert tuning.tuned_row_block("layer_norm", 768, rows=20,
                                      dtype="bfloat16") == 24
        assert tuning.tuned_row_block("layer_norm", 768, rows=8192,
                                      dtype="bfloat16") == 512
        # explicit request is honored verbatim
        assert tuning.tuned_row_block("layer_norm", 768, rows=20,
                                      dtype="bfloat16",
                                      requested=64) == 64


# --------------------------------------------------------------------------
# the in-process sweep property
# --------------------------------------------------------------------------

class TestInProcessSweep:
    def test_two_candidate_sweep_compiles_exactly_two(self, tables_dir,
                                                      rng):
        """A two-candidate block sweep traces exactly twice (one
        executable per candidate) and repeated calls hit the jit cache
        with NO cross-contamination — the property that lets a full
        sweep fit one process/window."""
        from apex1_tpu.ops.attention import flash_attention

        q = jnp.asarray(rng.normal(size=(1, 2, 128, 64)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(1, 2, 128, 64)), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(1, 2, 128, 64)), jnp.bfloat16)
        traces = []

        @functools.partial(jax.jit, static_argnames=("bq", "bk"))
        def run(q, k, v, bq, bk):
            traces.append((bq, bk))  # trace-time counter
            return flash_attention(q, k, v, causal=True,
                                   block_q=bq, block_k=bk)

        with force_impl("pallas"):
            a1 = np.asarray(run(q, k, v, 16, 16))
            b1 = np.asarray(run(q, k, v, 32, 32))
            # back to candidate 1: must be a cache hit serving candidate
            # 1's executable, not candidate 2's
            a2 = np.asarray(run(q, k, v, 16, 16))
            b2 = np.asarray(run(q, k, v, 32, 32))
        assert traces == [(16, 16), (32, 32)]
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(b1, b2)
        # both candidates computed the same attention (parity across
        # blocks), so the two executables are distinguishable only by
        # the trace counter — which is the point
        np.testing.assert_allclose(a1.astype(np.float32),
                                   b1.astype(np.float32),
                                   rtol=0.05, atol=0.05)

    @pytest.mark.slow  # 870s-cap headroom (23s: the full sweep
    # driver end-to-end); the sweep's load-bearing units stay tier-1
    # (trace-counter two-executable proof, lookup/persist round-trip,
    # VMEM filtering) and tables are still gated every check_all via
    # tune_kernels --validate
    def test_sweep_driver_attention_cpu(self, tables_dir):
        """The acceptance flow: a >=2-candidate in-process sweep on the
        cpu backend writes a winner a fresh lookup returns."""
        spec = importlib.util.spec_from_file_location(
            "_tune_for_test", _REPO / "tools" / "tune_kernels.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        lines = []
        winners, problems = mod.sweep_one(
            "attention", iters=1, say=lambda *a: lines.append(
                " ".join(str(x) for x in a)))
        assert problems == []
        assert len(winners) == 1   # cpu: one tiny shape case
        assert set(winners[0]) == {"block_q", "block_k"}
        text = "\n".join(lines)
        assert text.count(" ms fwd+bwd") >= 2   # >= 2 candidates timed
        assert "WINNER" in text and "lookup verified" in text
        # the winner persisted (keyed to its swept seq bucket) and a
        # cold lookup serves it
        tuning.clear_cache()
        assert tuning.lookup("flash_attention", {"Dp": 128, "Sb": 256},
                             "bfloat16") == winners[0]
        assert (tables_dir / "flash_attention.json").exists()
