"""Golden disagg-frontend fixture: the post-PR-16 handoff shape.

Carries every handoff guard: the max_handoff_attempts eviction rung on
the re-route ladder, the _live membership checks on both window-drain
paths, and the cancel-side window purge. Banks handoff/
handoff_failure/handoff_reroute/handoff_parity_mismatch/pool_shift.
Parse-only."""


class DisaggFrontend:
    def __init__(self, cfg, metrics):
        self.cfg = cfg
        self.metrics = metrics
        self._pending = []
        self._deferred = []
        self._live = set()
        self._attempts = {}

    def _start_handoff(self, rid, page):
        self.metrics.transition("handoff", req_id=rid)
        self._pending.append((rid, page))

    def _reroute(self, rid, cause):
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        if self._attempts[rid] > self.cfg.max_handoff_attempts:
            self.metrics.transition("handoff_failure", req_id=rid,
                                    failure=cause)
            return self._evict(rid)
        self.metrics.transition("handoff_reroute", req_id=rid,
                                cause=cause)
        return self._resubmit(rid)

    def _process_pending(self):
        for rid, page in list(self._pending):
            if rid not in self._live:
                continue
            self._install(rid, page)

    def _retry_deferred(self):
        for rid in list(self._deferred):
            if rid in self._live:
                self._resubmit(rid)

    def cancel(self, rid):
        self._pending = [(r, p) for r, p in self._pending if r != rid]
        self._live.discard(rid)

    def _check_parity(self, rid, got, want):
        if got != want:
            self.metrics.transition("handoff_parity_mismatch",
                                    req_id=rid)

    def _shift_pool(self, n):
        self.metrics.transition("pool_shift", n=n)

    def _install(self, rid, page):
        return rid

    def _resubmit(self, rid):
        return rid

    def _evict(self, rid):
        return rid
