"""Policy/controller version skew (must flag APX308).

The policy emits Action("shift_pool") but the controller _apply()
only dispatches escalate/deescalate — actuation raises ValueError at
runtime. Paired with autopilot_golden.py. Parse-only."""

MODES_DOWN = {"degraded": "shedding", "shedding": "normal"}


class Action:
    def __init__(self, kind, params=None):
        self.kind = kind
        self.params = params or {}


def _has_evidence(window, signal):
    return signal in window


def decide(state, window):
    if not _has_evidence(window, "fresh"):
        return []
    acts = []
    acts.extend(_escalation(state, window))
    acts.extend(_relaxation(state, window))
    return acts


def _escalation(state, window):
    if window.get("overload"):
        return [Action("escalate", {"to": "shedding"})]
    if window.get("prefill_pressure"):
        return [Action("shift_pool", {"n": 1})]
    return []


def _relaxation(state, window):
    if window.get("clear"):
        return [Action("deescalate",
                       {"to": MODES_DOWN.get(state.mode, "normal")})]
    return []


def _pool_ratio(state):
    if state.decode <= 1:
        return 0.0
    return state.prefill / state.decode


class AutopilotController:
    def __init__(self, metrics):
        self.metrics = metrics
        self.mode = "normal"

    def tick(self, state, window):
        for act in decide(state, window):
            self._apply(act)

    def _apply(self, act):
        if act.kind == "escalate":
            self.mode = act.params["to"]
        elif act.kind == "deescalate":
            self.mode = act.params["to"]
        else:
            raise ValueError(act.kind)
        self.metrics.transition("autopilot", action=act.kind)

    def _shift(self, n):
        return n
