"""PRE-fix PR 7 round-1 hedge gate (must flag APX306).

_hedge_blown_budgets() never consults first_token_seen(): a request
that is already streaming gets a duplicate full decode for zero
tail-latency win. Paired with frontend_golden.py. Parse-only."""


class ServingFrontend:
    def __init__(self, metrics):
        self.metrics = metrics
        self._route = {}
        self._shed_rids = set()
        self._subs = {}
        self._results = {}
        self._ttft = set()
        self._legs = None

    def submit(self, req):
        rep = self._pick_replica(req)
        if rep is None:
            rep = self._displace_sheddable(req)
        if rep is None:
            return None
        self._route[req.req_id] = rep
        return rep

    def _pick_replica(self, req):
        for rep in self._alive():
            if rep.load() < rep.capacity:
                return rep
        return None

    def _displace_sheddable(self, req):
        for rid, rep in list(self._route.items()):
            if rid in self._shed_rids:
                continue
            if rep.qos(rid) == "sheddable":
                self._shed_rids.add(rid)
                self.metrics.transition("shed", req_id=rid)
                return rep
        return None

    def _collect(self, rid):
        while self._legs.pending(rid):
            self._legs.wait(rid)
        return self._results.pop(rid)

    def _failover(self, rep):
        self.metrics.transition("failover", replica=rep.replica_id)
        orphans = [rid for rid, r in self._route.items() if r is rep]
        for rid in orphans:
            self._resubmit(rid)

    def _hedge_blown_budgets(self, routed):
        for rid in list(self._subs):
            for rep in self._alive():
                if rep.replica_id not in routed:
                    self.metrics.transition("hedge", req_id=rid)
                    self._route[rid] = rep
                    break

    def first_token_seen(self, rid):
        return rid in self._ttft

    def set_mode(self, mode):
        self.metrics.transition("mode", mode=mode)

    def _alive(self):
        return []

    def _resubmit(self, rid):
        return rid
