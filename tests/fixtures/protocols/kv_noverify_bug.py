"""PRE-fix PR 16 install path (must flag APX307).

install_page() trusts the page as extracted: a corruption in the
handoff window is installed into the decode pool's store and served
as silently corrupt KV. Paired with kv_golden.py. Parse-only."""


class HandoffError(Exception):
    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


def _digest(page):
    return sum(page)


def extract_page(store, rid):
    return store.get_prefix(rid)


def verify_page(manifest, page):
    if manifest.sha != _digest(page):
        raise HandoffError("integrity")


def install_page(store, manifest, page):
    store.put_prefix(manifest.rid, page)
