"""Golden kv_transfer fixture: verify-before-install (post-PR-16).

install_page() re-digests the page on arrival BEFORE it reaches the
decode pool's store — a wire corruption is a typed HandoffError, never
silently-served KV. Paired with kv_noverify_bug.py. Parse-only."""


class HandoffError(Exception):
    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


def _digest(page):
    return sum(page)


def extract_page(store, rid):
    return store.get_prefix(rid)


def verify_page(manifest, page):
    if manifest.sha != _digest(page):
        raise HandoffError("integrity")


def install_page(store, manifest, page):
    verify_page(manifest, page)
    store.put_prefix(manifest.rid, page)
