"""Golden scheduler fixture: the PRE-fix PR 7 round-1 shed ladder (must flag APX303).

The `<` skips only strictly-stronger entries, so an EQUAL-class
victim slips through the gate. Paired
with sched_golden.py. Parse-only."""


class QosScheduler:
    def __init__(self, capacity):
        self.capacity = capacity
        self._queue = []

    def _pick_shed_victim_locked(self, incoming_rank):
        victim = None
        for r in self._queue:
            if r.rank < incoming_rank:
                continue
            if victim is None or (r.rank, r.arrival) > (
                    victim.rank, victim.arrival):
                victim = r
        return victim

    def submit(self, req):
        if len(self._queue) < self.capacity:
            self._queue.append(req)
            return True
        victim = self._pick_shed_victim_locked(req.rank)
        if victim is None:
            return False
        self._queue.remove(victim)
        self._queue.append(req)
        return True

    def pop(self):
        if not self._queue:
            return None
        best = min(self._queue, key=lambda r: (r.rank, r.arrival))
        self._queue.remove(best)
        return best
