"""PRE-fix PR 16 re-route ladder (must flag APX307).

_reroute() has no max_handoff_attempts eviction rung: a persistently
failing handoff re-routes forever instead of surfacing a typed
eviction. Paired with disagg_golden.py. Parse-only."""


class DisaggFrontend:
    def __init__(self, cfg, metrics):
        self.cfg = cfg
        self.metrics = metrics
        self._pending = []
        self._deferred = []
        self._live = set()
        self._attempts = {}

    def _start_handoff(self, rid, page):
        self.metrics.transition("handoff", req_id=rid)
        self._pending.append((rid, page))

    def _reroute(self, rid, cause):
        self.metrics.transition("handoff_reroute", req_id=rid,
                                cause=cause)
        self.metrics.transition("handoff_failure", req_id=rid,
                                failure=cause)
        return self._resubmit(rid)

    def _process_pending(self):
        for rid, page in list(self._pending):
            if rid not in self._live:
                continue
            self._install(rid, page)

    def _retry_deferred(self):
        for rid in list(self._deferred):
            if rid in self._live:
                self._resubmit(rid)

    def cancel(self, rid):
        self._pending = [(r, p) for r, p in self._pending if r != rid]
        self._live.discard(rid)

    def _check_parity(self, rid, got, want):
        if got != want:
            self.metrics.transition("handoff_parity_mismatch",
                                    req_id=rid)

    def _shift_pool(self, n):
        self.metrics.transition("pool_shift", n=n)

    def _install(self, rid, page):
        return rid

    def _resubmit(self, rid):
        return rid

    def _evict(self, rid):
        return rid
