"""PRE-fix PR 16 cancel path (must flag APX304).

Both window guards are gone — cancel() purges neither the parked
handoff window nor the live set, and the window drain never checks
_live — so an acknowledged cancel's parked page is delivered and the
request re-admitted to the decode pool. Paired with disagg_golden.py.
Parse-only."""


class DisaggFrontend:
    def __init__(self, cfg, metrics):
        self.cfg = cfg
        self.metrics = metrics
        self._pending = []
        self._deferred = []
        self._live = set()
        self._attempts = {}

    def _start_handoff(self, rid, page):
        self.metrics.transition("handoff", req_id=rid)
        self._pending.append((rid, page))

    def _reroute(self, rid, cause):
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        if self._attempts[rid] > self.cfg.max_handoff_attempts:
            self.metrics.transition("handoff_failure", req_id=rid,
                                    failure=cause)
            return self._evict(rid)
        self.metrics.transition("handoff_reroute", req_id=rid,
                                cause=cause)
        return self._resubmit(rid)

    def _process_pending(self):
        for rid, page in list(self._pending):
            self._install(rid, page)

    def _retry_deferred(self):
        for rid in list(self._deferred):
            self._resubmit(rid)

    def cancel(self, rid):
        self._cancelled.add(rid)

    def _check_parity(self, rid, got, want):
        if got != want:
            self.metrics.transition("handoff_parity_mismatch",
                                    req_id=rid)

    def _shift_pool(self, n):
        self.metrics.transition("pool_shift", n=n)

    def _install(self, rid, page):
        return rid

    def _resubmit(self, rid):
        return rid

    def _evict(self, rid):
        return rid
