"""Unfenced publish shape (must flag APX302).

_iterate() lacks the generation fence, so a thread abandoned by a
kill keeps running and publishes a second terminal result after the
supervisor restarted. Paired with replica_golden.py. Parse-only."""


class ReplicaSupervisor:
    def __init__(self, cfg, metrics):
        self.cfg = cfg
        self.metrics = metrics
        self.replica_id = 0
        self.state = "alive"
        self.generation = 0
        self.restarts = 0
        self._inbox = []
        self._inflight = {}
        self._results = {}
        self._kill_counts = {}

    def cancel(self, rid):
        self._inbox.append(("cancel", rid))

    def mark_dead(self):
        self.state = "dead"
        self.metrics.transition("replica_dead", replica=self.replica_id)

    def restart(self):
        self.restarts += 1
        if self.restarts > self.cfg.max_restarts:
            self.state = "failed"
            self.metrics.transition("replica_failed",
                                    replica=self.replica_id)
            return False
        cancelled = [p for k, p in self._inbox if k == "cancel"]
        for rid in cancelled:
            self._inflight.pop(rid, None)
        for sub in list(self._inflight.values()):
            kills = self._kill_counts.get(sub.req_id, 0)
            if kills > self.cfg.poison_threshold:
                self._inflight.pop(sub.req_id, None)
        self._inbox.clear()
        self.generation += 1
        self.state = "alive"
        self.metrics.transition("replica_restart",
                                replica=self.replica_id)
        return True

    def drain_inflight(self):
        cancelled = [p for k, p in self._inbox if k == "cancel"]
        for rid in cancelled:
            self._inflight.pop(rid, None)
        subs = sorted(self._inflight.values(), key=lambda s: s.req_id)
        self._inflight.clear()
        self._inbox.clear()
        return subs

    def _iterate(self, gen):
        return self._step(gen)

    def _step(self, gen):
        return gen
