"""Model-drift fixture (must flag APX301).

A class that still matches the replica-family detection signature
(restart + drain_inflight) but lost the cancel/_iterate methods the
protocol model needs: the checker must refuse to silently skip it.
Parse-only."""


class ReplicaSupervisor:
    def restart(self):
        return True

    def drain_inflight(self):
        return []
