"""Adapter-page publish protocol — BUG fixture (torn page publish).

The one-moved-statement mutation of ``lora_page_publish_golden.py``:
the staging write that re-fills a slot with the next adapter page
payload has been hoisted ABOVE the semaphore wait that licenses slot
reuse.  The publish DMA started two steps ago may still be reading the
slot when it is overwritten, so the page that lands in the
device-visible pool can interleave old and new payload rows — a decode
step whose LoRA block-table row already names that page gathers torn
adapter weights.  graftlint's APX2xx bounded model checker must flag
exactly this line as APX202 (write to a buffer a DMA is still reading
it) at ring size 3.

Fixture only — never imported by the library; exercised from
``tests/test_lint_kernels.py::TestLoraPagePublishFixtures``.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(page_ref, o_ref, pg_stage, pg_pool, pub_sem):
    t = pl.program_id(0)
    T = pl.num_programs(0)
    slot = jax.lax.rem(t, 2)
    nxt = jax.lax.rem(t + 1, 2)

    def publish(s):
        return pltpu.make_async_copy(
            pg_stage.at[s], pg_pool.at[s], pub_sem.at[s])

    pg_stage[slot] = page_ref[...]   # BUG: torn adapter-page publish —
    #                                  the publish from two steps ago
    #                                  may still be reading this slot

    @pl.when(t >= 2)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

    publish(slot).start()

    o_ref[...] = page_ref[...]

    @pl.when(t == T - 1)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

        @pl.when(T > 1)
        def _():
            pltpu.semaphore_wait(pub_sem.at[nxt], 2)


def publish_adapter_pages(pages, n_steps):
    return pl.pallas_call(
        _kernel,
        grid=(n_steps,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(pages)
