"""Adapter-page publish protocol — GOLDEN fixture (must lint clean).

A structural model of the multi-tenant LoRA control plane's device-side
adapter-page publish loop (`serving.lora.LoraAdapterStore.register`,
phase 1): each grid step stages one adapter page payload in a VMEM
staging slot and DMAs it into the device-visible page pool,
double-buffered across two slots so the next page can be staged while
the previous publish drains.  The property under test is slot-reuse
ordering: the write that re-stages a slot is program-ordered AFTER the
semaphore wait that retires the publish still reading that slot (a
local async copy delivers +2 on its semaphore — send and recv halves —
so the reuse wait consumes 2).

The paired ``lora_page_publish_torn_page_bug.py`` fixture moves that
write above the wait: the in-flight DMA can then read a half-updated
page payload — a decode step whose block-table row already names the
page would gather torn adapter weights, exactly the torn-publish race
the store's write-payloads-then-publish-row discipline exists to keep
off the host path.  This file is the clean half of the pair;
graftlint's APX2xx checker (``lint_sources(..., kernels=True)``) must
report NO findings on it.

Fixture only — never imported by the library; exercised from
``tests/test_lint_kernels.py::TestLoraPagePublishFixtures``.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(page_ref, o_ref, pg_stage, pg_pool, pub_sem):
    t = pl.program_id(0)
    T = pl.num_programs(0)
    slot = jax.lax.rem(t, 2)
    nxt = jax.lax.rem(t + 1, 2)

    def publish(s):
        return pltpu.make_async_copy(
            pg_stage.at[s], pg_pool.at[s], pub_sem.at[s])

    # License slot reuse: the publish started two steps ago from this
    # slot must have fully retired before the payload is rewritten.
    @pl.when(t >= 2)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

    pg_stage[slot] = page_ref[...]
    publish(slot).start()

    o_ref[...] = page_ref[...]

    # Drain: the last two publishes are still in flight at exit.
    @pl.when(t == T - 1)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

        @pl.when(T > 1)
        def _():
            pltpu.semaphore_wait(pub_sem.at[nxt], 2)


def publish_adapter_pages(pages, n_steps):
    return pl.pallas_call(
        _kernel,
        grid=(n_steps,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.VMEM((2, 8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(pages)
