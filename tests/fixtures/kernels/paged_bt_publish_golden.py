"""Block-table publish protocol — GOLDEN fixture (must lint clean).

A structural model of the paged-decode control plane's device-side
block-table publish loop: each grid step stages one block-table row in
a VMEM staging slot and DMAs it to the pool's device-visible mirror,
double-buffered across two slots so the next row can be staged while
the previous publish drains.  The property under test is slot-reuse
ordering: the write that re-stages a slot is program-ordered AFTER the
semaphore wait that retires the publish still reading that slot (a
local async copy delivers +2 on its semaphore — send and recv halves —
so the reuse wait consumes 2).

The paired ``paged_bt_publish_torn_bt_bug.py`` fixture moves that
write above the wait: the in-flight DMA can then read a half-updated
block-table row — the torn block-table read APX202 exists to catch.
This file is the clean half of the pair; graftlint's APX2xx checker
(``lint_sources(..., kernels=True)``) must report NO findings on it.

Fixture only — never imported by the library; exercised from
``tests/test_lint_kernels.py::TestPagedBtPublishFixtures``.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(bt_ref, o_ref, bt_stage, bt_shadow, pub_sem):
    t = pl.program_id(0)
    T = pl.num_programs(0)
    slot = jax.lax.rem(t, 2)
    nxt = jax.lax.rem(t + 1, 2)

    def publish(s):
        return pltpu.make_async_copy(
            bt_stage.at[s], bt_shadow.at[s], pub_sem.at[s])

    # License slot reuse: the publish started two steps ago from this
    # slot must have fully retired before the row is rewritten.
    @pl.when(t >= 2)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

    bt_stage[slot] = bt_ref[...]
    publish(slot).start()

    o_ref[...] = bt_ref[...]

    # Drain: the last two publishes are still in flight at exit.
    @pl.when(t == T - 1)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

        @pl.when(T > 1)
        def _():
            pltpu.semaphore_wait(pub_sem.at[nxt], 2)


def publish_block_tables(bt, n_steps):
    return pl.pallas_call(
        _kernel,
        grid=(n_steps,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, 128), jnp.int32),
            pltpu.VMEM((2, 8, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(bt)
