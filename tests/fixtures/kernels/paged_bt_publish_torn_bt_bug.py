"""Block-table publish protocol — TORN-READ BUG fixture (must flag).

Identical to ``paged_bt_publish_golden.py`` except for one moved
statement: the slot-reuse write lands BEFORE the semaphore wait that
retires the previous publish from the same slot.  The DMA started two
grid steps ago can still be reading ``bt_stage[slot]`` when the new
row is written over it — the device-visible block-table mirror then
receives a half-old/half-new row, and the decode kernel walking it
attends to pages the row never legitimately named.  This is the torn
block-table read; graftlint MUST flag it as APX202 (dma-race) at the
write line (first reproduces at ring size n=3: the t=0 publish still
in flight when t=2 re-stages slot 0).

Fixture only — never imported by the library; exercised from
``tests/test_lint_kernels.py::TestPagedBtPublishFixtures``.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(bt_ref, o_ref, bt_stage, bt_shadow, pub_sem):
    t = pl.program_id(0)
    T = pl.num_programs(0)
    slot = jax.lax.rem(t, 2)
    nxt = jax.lax.rem(t + 1, 2)

    def publish(s):
        return pltpu.make_async_copy(
            bt_stage.at[s], bt_shadow.at[s], pub_sem.at[s])

    bt_stage[slot] = bt_ref[...]   # BUG: torn block-table read — the
    #                                publish from two steps ago may
    #                                still be reading this slot

    @pl.when(t >= 2)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

    publish(slot).start()

    o_ref[...] = bt_ref[...]

    @pl.when(t == T - 1)
    def _():
        pltpu.semaphore_wait(pub_sem.at[slot], 2)

        @pl.when(T > 1)
        def _():
            pltpu.semaphore_wait(pub_sem.at[nxt], 2)


def publish_block_tables(bt, n_steps):
    return pl.pallas_call(
        _kernel,
        grid=(n_steps,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, 128), jnp.int32),
            pltpu.VMEM((2, 8, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(bt)
