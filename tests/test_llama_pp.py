"""Llama over the pipeline schedule — BASELINE config 4 shape ("Llama-3
8B with TP/PP on XLA mesh") at tiny size: transformer blocks sharded into
pipeline stages via `pipeline_apply` (scan+ppermute 1F1B-equivalent),
embedding/head replicated. Parity vs the unpartitioned model, fwd + grads.
(TP parity is covered in test_llama.py via GSPMD param_specs; the
TP×PP×DP×SP composition compiles in __graft_entry__.dryrun_multichip.)"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.models.llama import Llama, LlamaBlock, LlamaConfig
from apex1_tpu.ops import rope_tables, softmax_cross_entropy_loss
from apex1_tpu.transformer.pipeline_parallel.schedules import pipeline_apply

pytestmark = pytest.mark.slow  # composed-step / fuzz suite: full run via check_all.sh --all

PP = 2
LAYERS = 4
LPS = LAYERS // PP  # layers per stage


def _stack_stage_params(params):
    """{layer0..3} -> per-leaf (V=1, PP, LPS, ...) chunk-stacked tree."""
    layers = [params[f"layer{i}"] for i in range(LAYERS)]
    grouped = [layers[s * LPS:(s + 1) * LPS] for s in range(PP)]

    def stack(*leaves):
        arr = np.stack([np.stack([np.asarray(l) for l in stage])
                        for stage in
                        [[jax.tree.leaves(g[j])[0] for j in range(LPS)]
                         for g in [None]]])
        return arr

    # stack leaf-wise across (stage, layer-in-stage)
    return jax.tree.map(
        lambda *ls: jnp.stack(
            [jnp.stack(ls[s * LPS:(s + 1) * LPS]) for s in range(PP)]
        )[None],  # leading V=1
        *layers)


def test_llama_pipeline_matches_unpartitioned(devices):
    cfg = LlamaConfig.tiny(num_layers=LAYERS)
    model = Llama(cfg)
    rng = np.random.default_rng(3)
    B, S = 2, 32
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)),
                         jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]
    mesh = make_mesh(pp=PP, dp=1, devices=devices[:PP])

    stage_stacked = _stack_stage_params(params)
    block = LlamaBlock(cfg)
    cos, sin = rope_tables(jnp.arange(S), cfg.head_dim, base=cfg.rope_base)

    def loss_of_logits(logits, tokens):
        return jnp.mean(softmax_cross_entropy_loss(
            logits[:, :-1].astype(jnp.float32), tokens[:, 1:]))

    def pp_forward(params, stage_params, tokens):
        # embedding + final norm/head replicated; blocks pipelined
        emb = params["tok_embeddings"]
        x = emb[tokens]

        def stage_fn(p_stage, x):
            for j in range(LPS):
                layer_p = jax.tree.map(lambda l, j=j: l[0, j], p_stage)
                x = block.apply({"params": layer_p}, x, cos, sin)
            return x

        x = pipeline_apply(stage_fn, stage_params, x[None],
                           num_chunks=1)[0]
        from apex1_tpu.ops import rms_norm
        x = rms_norm(x, params["norm"], eps=cfg.norm_eps)
        logits = x @ params["output"].T
        return loss_of_logits(logits, tokens)

    pp_loss = jax.jit(jax.shard_map(
        pp_forward, mesh=mesh, in_specs=(P(), P(None, "pp"), P()),
        out_specs=P(), check_vma=False))

    def full_loss(params, tokens):
        logits = model.apply({"params": params}, tokens)
        return loss_of_logits(logits, tokens)

    got = float(pp_loss(params, stage_stacked, tokens))
    want = float(full_loss(params, tokens))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # grad parity through the pipeline (embedding + one stage leaf)
    g_pp = jax.grad(lambda p: pp_loss(p, _stack_stage_params(p), tokens))(
        params)
    g_full = jax.grad(lambda p: full_loss(p, tokens))(params)
    for key in ("tok_embeddings", "output", "norm"):
        np.testing.assert_allclose(
            np.asarray(g_pp[key]), np.asarray(g_full[key]),
            rtol=2e-4, atol=1e-5, err_msg=key)
    for lyr in ("layer0", f"layer{LAYERS - 1}"):
        for leaf in ("wq", "w_down", "attn_norm"):
            np.testing.assert_allclose(
                np.asarray(g_pp[lyr][leaf]), np.asarray(g_full[lyr][leaf]),
                rtol=2e-4, atol=1e-5, err_msg=f"{lyr}/{leaf}")


def test_llama_pipeline_microbatched(devices):
    """M=4 microbatches through the pipe ≡ the full-batch model."""
    cfg = LlamaConfig.tiny(num_layers=LAYERS)
    model = Llama(cfg)
    rng = np.random.default_rng(5)
    M, B, S = 4, 1, 16
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (M, B, S)),
                         jnp.int32)
    params = model.init(jax.random.key(0), tokens[0])["params"]
    mesh = make_mesh(pp=PP, dp=1, devices=devices[:PP])
    stage_stacked = _stack_stage_params(params)
    block = LlamaBlock(cfg)
    cos, sin = rope_tables(jnp.arange(S), cfg.head_dim, base=cfg.rope_base)

    def pp_hidden(params, stage_params, tokens):
        x = params["tok_embeddings"][tokens]  # (M, B, S, E)

        def stage_fn(p_stage, x):
            for j in range(LPS):
                layer_p = jax.tree.map(lambda l, j=j: l[0, j], p_stage)
                x = block.apply({"params": layer_p}, x, cos, sin)
            return x

        return pipeline_apply(stage_fn, stage_params, x, num_chunks=1)

    fn = jax.jit(jax.shard_map(
        pp_hidden, mesh=mesh, in_specs=(P(), P(None, "pp"), P()),
        out_specs=P(), check_vma=False))
    got = fn(params, stage_stacked, tokens)

    # reference: run each microbatch through the blocks directly
    def blocks_only(params, t):
        x = params["tok_embeddings"][t]
        for i in range(LAYERS):
            x = LlamaBlock(cfg).apply({"params": params[f"layer{i}"]},
                                      x, cos, sin)
        return x

    for m in range(M):
        want = blocks_only(params, tokens[m])
        np.testing.assert_allclose(np.asarray(got[m]), np.asarray(want),
                                   rtol=2e-5, atol=2e-5, err_msg=f"mb{m}")
