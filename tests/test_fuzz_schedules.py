"""Property-based fuzzing of the TRUE 1F1B schedule (V=1 and the
interleaved group-cycled V>1 form) against the flat composition —
randomized (V, P, M, width, skip_idle) draws catch clocking/FIFO/ring
bugs the fixed-parameter parity tests can't (ring slot reuse at odd
M/P ratios, chunk recirculation timing at V=3, masked-vs-cond drift)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from apex1_tpu.core.mesh import make_mesh  # noqa: E402
from apex1_tpu.transformer.pipeline_parallel import schedules  # noqa: E402

pytestmark = pytest.mark.slow  # fuzz suite: full run via check_all.sh --all

# 4 examples/property (was 6): every example compiles a fresh pipeline
# scan; wall-time budget per VERDICT r3 Weak #5. APEX1_FUZZ_EXAMPLES
# overrides for deep one-off hunts.
_SETTINGS = dict(
    max_examples=int(os.environ.get("APEX1_FUZZ_EXAMPLES") or "4"),
    deadline=None, suppress_health_check=list(HealthCheck))


@settings(**_SETTINGS)
@given(
    v=st.sampled_from([1, 2, 3]),
    p=st.sampled_from([2, 4]),
    groups=st.integers(1, 3),
    d=st.sampled_from([4, 8]),
    mb=st.integers(1, 3),
    skip=st.booleans(),
    seed=st.integers(0, 2 ** 16),
)
def test_one_f_one_b_matches_flat(v, p, groups, d, mb, skip, seed):
    from jax.sharding import PartitionSpec as Ps

    M = groups * p  # interleaved requires M % P == 0; harmless at V=1
    mesh = make_mesh(pp=p, devices=jax.devices()[:p])
    rng = np.random.default_rng(seed)
    params = {
        "w": jnp.asarray(rng.normal(size=(v, p, d, d)) * 0.5,
                         jnp.float32),
        "b": jnp.asarray(rng.normal(size=(v, p, d)) * 0.1, jnp.float32)}
    mbs = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)

    def stage(pr, x):
        return jnp.tanh(x @ pr["w"] + pr["b"])

    def loss_mb(y, m):
        t = jax.lax.dynamic_index_in_dim(tgt, m, 0, keepdims=False)
        return jnp.mean(jnp.square(y - t)) / M

    def inner(params, mbs):
        # V=1 drops the chunk axis (the non-interleaved signature);
        # V>1 keeps it with the stage axis sharded away
        if v == 1:
            local = jax.tree_util.tree_map(lambda pr: pr[0, 0], params)
        else:
            local = jax.tree_util.tree_map(lambda pr: pr[:, 0], params)
        loss, grads, dmb = schedules.one_f_one_b(
            stage, local, mbs, loss_mb, num_chunks=v, skip_idle=skip)
        if v == 1:
            grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        return (jax.lax.psum(loss, "pp"),
                jax.tree_util.tree_map(lambda g: g[:, None], grads),
                dmb)

    pspec = jax.tree_util.tree_map(lambda _: Ps(None, "pp"), params)
    loss, grads, dmb = jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(pspec, Ps()),
        out_specs=(Ps(), pspec, Ps()), check_vma=False))(params, mbs)

    def flat(params, mbs):
        def one(x, t):
            for vv in range(v):
                for s in range(p):
                    x = stage(jax.tree_util.tree_map(
                        lambda pr: pr[vv, s], params), x)
            return jnp.mean(jnp.square(x - t)) / M
        return jnp.sum(jax.vmap(one)(mbs, tgt))

    want, (gp, gx) = jax.value_and_grad(flat, argnums=(0, 1))(params,
                                                              mbs)
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(gp[k]), rtol=2e-5,
                                   atol=2e-6, err_msg=f"{k} V={v} P={p}")
    np.testing.assert_allclose(np.asarray(dmb), np.asarray(gx),
                               rtol=2e-5, atol=2e-6)


@settings(**_SETTINGS)
@given(
    v=st.sampled_from([1, 2]),
    p=st.sampled_from([2, 4]),
    m_extra=st.integers(0, 3),
    d=st.sampled_from([4, 8]),
    pad=st.integers(0, 3),
    skip=st.booleans(),
    seed=st.integers(0, 2 ** 16),
)
def test_pipeline_apply_matches_flat(v, p, m_extra, d, pad, skip, seed):
    """The scan schedule under fuzzed (V, P, M, boundary padding,
    skip_bubbles): grad-outside convention vs the flat composition,
    including pad-to-max boundaries wider than the microbatch."""
    from jax.sharding import PartitionSpec as Ps

    M = max(p, 2) + m_extra if v > 1 else 2 + m_extra  # V>1 needs M>=P
    mb = 2
    mesh = make_mesh(pp=p, devices=jax.devices()[:p])
    rng = np.random.default_rng(seed)
    params = {"w": jnp.asarray(rng.normal(size=(v, p, d, d)) * 0.5,
                               jnp.float32)}
    mbs = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)
    D_b = d + pad    # boundary wider than the microbatch when pad > 0

    def stage(pr, x):
        # operate on the real d columns, pass the pad region through
        y = jnp.tanh(x[..., :d] @ pr["w"])
        return jnp.concatenate([y, x[..., d:]], axis=-1)

    def pipe_loss(params, mbs):
        def inner(params, mbs):
            local = jax.tree_util.tree_map(lambda pr: pr[:, 0], params)
            outs = schedules.pipeline_apply(
                stage, local, mbs, num_chunks=v, skip_bubbles=skip,
                boundary_shape=(mb, D_b) if pad else None)
            return jnp.mean(jnp.square(outs[..., :d] - tgt))

        return jax.shard_map(
            inner, mesh=mesh, in_specs=(Ps(None, "pp"), Ps()),
            out_specs=Ps(), check_vma=False)(params, mbs)

    loss, grads = jax.jit(jax.value_and_grad(pipe_loss))(params, mbs)

    def flat(params, mbs):
        def one(x, t):
            for vv in range(v):
                for s in range(p):
                    x = jnp.tanh(x @ params["w"][vv, s])
            return jnp.mean(jnp.square(x - t))
        return jnp.mean(jax.vmap(one)(mbs, tgt))

    want, gw = jax.value_and_grad(flat)(params, mbs)
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               np.asarray(gw["w"]), rtol=2e-5,
                               atol=2e-6)
