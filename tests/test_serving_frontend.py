"""`apex1_tpu.serving.replica` + `serving.frontend` — the fault
boundary of the serving tier, driven deterministically (pump mode; the
chaos faults fire at exact (replica, step) coordinates).

The model throughout is `testing.chaos.toy_decoder`: a deterministic
history-dependent cached decoder that compiles in milliseconds, so
these drills pay supervisor cost, not XLA cost. The REAL-model
acceptance drill (tiny GPT-2, bit-parity vs solo generate) lives in
``test_serving.py::TestReplicaKillDrill``.
"""

import time

import numpy as np
import pytest

from apex1_tpu.serving import (Backpressure, DegradeProfile, Engine,
                               EngineConfig, FrontendConfig,
                               ReplicaConfig, ServingFrontend)
from apex1_tpu.testing.chaos import (ChaosSchedule, PoisonPill,
                                     ReplicaHang, ReplicaKill,
                                     SlowReplica, kill_schedule,
                                     toy_decoder)

VOCAB = 61


@pytest.fixture(scope="module")
def toy():
    return toy_decoder(VOCAB)


def _make_engine_factory(toy, **ekw):
    apply_fn, make_cache, params = toy
    kw = dict(max_slots=3, max_len=48, prefill_chunk=4,
              vocab_size=VOCAB, temperature=0.8, seed=7)
    kw.update(ekw)

    def make_engine(cache_dtype=None):
        return Engine(apply_fn, make_cache, params, EngineConfig(**kw),
                      cache_dtype=cache_dtype)

    return make_engine


def _reference(make_engine, front, rids):
    """Uninterrupted single-engine run of each request (same seed)."""
    ref = make_engine()
    out = {}
    for rid in rids:
        sub = front._subs[rid]
        rr = ref.submit(sub.tokens, max_new_tokens=sub.max_new_tokens,
                        seed=sub.seed)
        ref.run(max_steps=200)
        out[rid] = ref.results[rr].tokens
    return out


def _submit_mix(front, rng, n, *, new=8, qos="best_effort"):
    prompts = [rng.integers(0, VOCAB, (3 + i % 5,)).astype(np.int32)
               for i in range(n)]
    return [front.submit(p, max_new_tokens=new + i % 3, qos=qos)
            for i, p in enumerate(prompts)]


class TestSupervisorRecovery:
    def test_watchdog_declares_hang_dead_then_restart_completes(
            self, toy, rng):
        """The watchdog path: a replica that stops making step progress
        (hang > watchdog_s) is declared dead even though it never
        raised; restart + resubmission completes every stream
        token-identically."""
        make_engine = _make_engine_factory(toy)
        hang = ReplicaHang(replica=0, at_step=4, hang_s=0.25)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=1, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=0.1)),
            fault=hang)
        rids = _submit_mix(front, rng, 4)
        front.run_until_drained(timeout_s=60.0)
        assert hang.fired == 1
        assert front.replicas[0].restarts == 1
        assert front.replicas[0].engines_built == 2
        want = _reference(make_engine, front, rids)
        for rid in rids:
            res = front.poll(rid)
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, want[rid])
        deaths = [t for t in front.metrics.transitions
                  if t["event"] == "replica_dead"]
        assert len(deaths) == 1 and "watchdog" in deaths[0]["error"]

    def test_slow_replica_stays_alive_no_restart(self, toy, rng):
        """A straggler below the watchdog threshold is degraded, not
        dead: no restart, results correct — the case hedging (not
        supervision) exists for."""
        make_engine = _make_engine_factory(toy)
        slow = SlowReplica(0, delay_s=0.01, from_step=0, to_step=20)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=1, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=5.0)),
            fault=slow)
        rids = _submit_mix(front, rng, 3)
        front.run_until_drained(timeout_s=60.0)
        assert front.replicas[0].restarts == 0
        assert front.replicas[0].state == "alive"
        assert all(front.poll(r).status == "done" for r in rids)

    def test_failover_reroutes_when_restart_budget_spent(self, toy, rng):
        """max_restarts=0: the killed replica goes straight to
        ``failed``; the frontend drains its in-flight submissions and
        re-routes them to the survivor — same ids, same seeds, so the
        streams still come out token-identical."""
        make_engine = _make_engine_factory(toy)
        kill = ReplicaKill(replica=0, at_step=3)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0,
                                                 max_restarts=0)),
            fault=kill)
        rids = _submit_mix(front, rng, 6)
        front.run_until_drained(timeout_s=60.0)
        assert front.replica_states() == ["failed", "alive"]
        assert front.replicas[0].engines_built == 1   # never rebuilt
        want = _reference(make_engine, front, rids)
        for rid in rids:
            res = front.poll(rid)
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, want[rid])
        fo = [t for t in front.metrics.transitions
              if t["event"] == "failover"]
        assert len(fo) == 1 and fo[0]["source"] == 0
        assert len(fo[0]["rerouted"]) > 0

    def test_poison_quarantine_bounds_crash_loop(self, toy, rng):
        """A request whose admission kills the replica every time is
        quarantined past poison_threshold instead of crash-looping;
        innocent requests on the same replica still finish."""
        make_engine = _make_engine_factory(toy)
        pill = PoisonPill(poison_token=60)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=1, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0,
                                                 max_restarts=5,
                                                 poison_threshold=1)),
            fault=pill)
        # good prompts drawn BELOW the poison token — the pill must be
        # the only pill
        good = [front.submit(rng.integers(0, 59, (4 + i,)),
                             max_new_tokens=6) for i in range(2)]
        bad = front.submit(np.asarray([60, 4], np.int32),
                           max_new_tokens=5)
        front.run_until_drained(timeout_s=60.0)
        res = front.poll(bad)
        assert res.status == "evicted" and "poisoned" in res.reason
        assert pill.fired == 2                    # threshold + 1
        assert front.replicas[0].restarts == 2
        assert front.replicas[0].state == "alive"  # budget NOT spent
        assert all(front.poll(r).status == "done" for r in good)

    def test_kill_schedule_is_seed_deterministic(self):
        a = kill_schedule(42, n_replicas=4, lo=3, hi=11)
        b = kill_schedule(42, n_replicas=4, lo=3, hi=11)
        c = kill_schedule(43, n_replicas=4, lo=3, hi=11)
        assert (a.replica, a.at_step) == (b.replica, b.at_step)
        assert 0 <= a.replica < 4 and 3 <= a.at_step < 11
        assert (a.replica, a.at_step) != (c.replica, c.at_step)


class TestHedging:
    def test_hedge_fires_on_blown_ttft_budget_and_hedge_leg_wins(
            self, toy, rng):
        """The hedge trigger is a TTFT budget: replica 0 dies BEFORE
        producing the request's first token (kill at step 0), so the
        budget blows and the request is duplicated to replica 1; a
        second kill then delays the restarted primary further, so the
        hedge leg finishes first — first answer wins, tokens identical
        by construction, loser cancelled."""
        make_engine = _make_engine_factory(toy)
        kills = ChaosSchedule([ReplicaKill(replica=0, at_step=0),
                               ReplicaKill(replica=0, at_step=2)])
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=0.0,    # any wait blows it
                           replica=ReplicaConfig(watchdog_s=60.0)),
            fault=kills)
        p = rng.integers(0, VOCAB, (5,)).astype(np.int32)
        rid = front.submit(p, max_new_tokens=10, qos="guaranteed")
        front.run_until_drained(timeout_s=60.0)
        res = front.poll(rid)
        assert res.status == "done"
        want = _reference(make_engine, front, [rid])[rid]
        np.testing.assert_array_equal(res.tokens, want)
        s = front.summary()["counters"]
        assert s["hedges_fired"] == 1
        assert s["hedges_won"] == 1               # the hedge leg won
        hedges = [t for t in front.metrics.transitions
                  if t["event"] == "hedge"]
        assert len(hedges) == 1 and hedges[0]["req"] == rid

    def test_streaming_primary_is_never_hedged(self, toy, rng):
        """A slow-but-streaming primary must NOT trigger a hedge — the
        budget is time-to-FIRST-token, not time-to-completion
        (review finding: elapsed-time hedging doubled every long
        guaranteed decode)."""
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=0.0,
                           replica=ReplicaConfig(watchdog_s=60.0)))
        p = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        rid = front.submit(p, max_new_tokens=12, qos="guaranteed")
        front.run_until_drained(timeout_s=60.0)
        assert front.poll(rid).status == "done"
        # first token landed on the first pump; every later round was
        # past the 0-second budget yet no hedge fired
        assert front.summary()["counters"]["hedges_fired"] == 0

    def test_best_effort_never_hedged(self, toy, rng):
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=0.0,
                           replica=ReplicaConfig(watchdog_s=60.0)))
        rids = _submit_mix(front, rng, 3, qos="best_effort")
        front.run_until_drained(timeout_s=60.0)
        assert front.summary()["counters"]["hedges_fired"] == 0
        assert all(front.poll(r).status == "done" for r in rids)


class TestOverloadDrill:
    def _overloaded_front(self, toy, *, enter_shed=0.6,
                          enter_degraded=0.9, cache_dtype=None,
                          cap=3):
        make_engine = _make_engine_factory(toy)
        return make_engine, ServingFrontend(
            make_engine,
            FrontendConfig(
                n_replicas=1, capacity_per_replica=4, seed=3,
                hedge_after_s=None, enter_shed=enter_shed,
                enter_degraded=enter_degraded, exit_overload=0.25,
                sustain_rounds=2,
                degrade=DegradeProfile(max_new_tokens_cap=cap,
                                       cache_dtype=cache_dtype),
                replica=ReplicaConfig(watchdog_s=60.0)))

    def test_sheddable_shed_before_guaranteed_misses_deadline(
            self, toy, rng):
        """THE overload acceptance drill: at capacity, guaranteed
        arrivals displace sheddable load (shed first, banked);
        sustained overload flips the mode ladder with every transition
        banked as a JSON metrics event; every guaranteed request
        completes within its deadline; de-escalation back to normal is
        banked too."""
        make_engine, front = self._overloaded_front(toy)
        shed_rids = _submit_mix(front, rng, 4, new=12, qos="sheddable")
        deadline = time.monotonic() + 30.0
        g_rids = [front.submit(
            rng.integers(0, VOCAB, (4,)).astype(np.int32),
            max_new_tokens=6, qos="guaranteed", deadline=deadline)
            for _ in range(2)]
        # displacement already happened at submit: capacity 4 held 4
        # sheddable, 2 guaranteed arrivals shed the 2 youngest
        assert front.summary()["counters"]["sheds"] >= 2
        front.run_until_drained(timeout_s=60.0)
        done_at = time.monotonic()
        for rid in g_rids:
            res = front.poll(rid)
            assert res.status == "done", (rid, res)
        assert done_at < deadline          # ...within the deadline
        shed = [front.poll(r) for r in shed_rids]
        assert all(r.status in ("evicted", "done") for r in shed)
        assert any(r.status == "evicted" and "shed" in r.reason
                   for r in shed)
        # no guaranteed request was ever evicted or rejected
        assert all(front.poll(r).status == "done" for r in g_rids)
        events = front.metrics.transitions
        mode_flips = [t for t in events if t["event"] == "mode"]
        assert any(t["to"] == "shedding" for t in mode_flips)
        sheds = [t for t in events if t["event"] == "shed"]
        assert len(sheds) == front.summary()["counters"]["sheds"]
        # drain -> de-escalation is banked as well
        front.pump(6)
        mode_flips = [t for t in front.metrics.transitions
                      if t["event"] == "mode"]
        assert mode_flips[-1]["to"] == "normal"
        assert front.mode == "normal"

    def test_degraded_mode_caps_admissions_and_rejects_sheddable(
            self, toy, rng):
        """Degraded mode is pressure relief, not failure: new
        admissions keep flowing with max_new_tokens capped to the
        profile; sheddable-class admissions get a structured 429."""
        make_engine, front = self._overloaded_front(
            toy, enter_shed=0.4, enter_degraded=0.5, cap=3)
        rids = _submit_mix(front, rng, 3, new=12)   # 3/4 of capacity
        front.pump(4)                      # sustain -> shedding -> degraded
        assert front.mode == "degraded"
        capped = front.submit(rng.integers(0, VOCAB, (4,)),
                              max_new_tokens=12)
        with pytest.raises(Backpressure, match="sheddable"):
            front.submit(rng.integers(0, VOCAB, (3,)),
                         max_new_tokens=4, qos="sheddable")
        front.run_until_drained(timeout_s=60.0)
        assert front.poll(capped).tokens.size == 3   # the cap, not 12
        assert all(front.poll(r).status == "done" for r in rids)
        flips = [t for t in front.metrics.transitions
                 if t["event"] == "mode"]
        deg = next(t for t in flips if t["to"] == "degraded")
        assert deg["max_new_tokens_cap"] == 3
        assert front.summary()["counters"]["degraded_admissions"] == 1

    def test_degraded_restart_rides_quantized_kv_profile(self, toy,
                                                         rng):
        """A replica (re)built while degraded gets the profile's
        cache_dtype (the int8-KV relief valve) — and the toy cache
        stores small exact ints, so the resubmitted streams stay
        token-identical across the dtype flip."""
        import jax
        import jax.numpy as jnp
        make_engine, front = self._overloaded_front(
            toy, enter_shed=0.4, enter_degraded=0.5,
            cache_dtype=jnp.int8)
        kill = ReplicaKill(replica=0, at_step=6)
        front.replicas[0].fault = kill
        rids = _submit_mix(front, rng, 4, new=10)
        front.pump(4)
        assert front.mode == "degraded"
        leaf0 = jax.tree_util.tree_leaves(
            front.replicas[0].engine.kv.cache)[0]
        assert leaf0.dtype == jnp.float32         # built before the flip
        front.run_until_drained(timeout_s=60.0)
        assert kill.fired == 1 and front.replicas[0].restarts == 1
        leaf1 = jax.tree_util.tree_leaves(
            front.replicas[0].engine.kv.cache)[0]
        assert leaf1.dtype == jnp.int8            # rebuilt ON the profile
        want = _reference(make_engine, front, rids)
        for rid in rids:
            res = front.poll(rid)
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, want[rid])


class TestReviewRegressions:
    def test_oversized_seed_folds_instead_of_crashing(self, toy):
        """A 64-bit explicit seed must not pass admission and then
        crash the engine step (under a supervisor that reads as a
        replica crash loop) — it folds to int32 deterministically."""
        apply_fn, make_cache, params = toy
        kw = dict(max_slots=2, max_len=48, prefill_chunk=4,
                  vocab_size=VOCAB, temperature=0.9)
        big = 2 ** 31 + 12345
        outs = []
        for _ in range(2):
            eng = Engine(apply_fn, make_cache, params,
                         EngineConfig(**kw))
            rid = eng.submit([7, 3, 9], max_new_tokens=8, seed=big)
            eng.run(max_steps=40)
            res = eng.results[rid]
            assert res.status == "done"
            outs.append(res.tokens)
        np.testing.assert_array_equal(*outs)   # folded, still pinned

    def test_cancel_pending_at_restart_is_not_resurrected(self, toy,
                                                          rng):
        """An acknowledged cancel sitting in the inbox when the
        replica dies must survive the restart — resubmitting the
        request from inflight would resurrect cancelled work."""
        from apex1_tpu.serving import ReplicaSupervisor
        make_engine = _make_engine_factory(toy)
        sup = ReplicaSupervisor(make_engine, 0,
                                config=ReplicaConfig(watchdog_s=60.0))
        keep = sup.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=6)
        dead = sup.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=20)
        sup.pump(2)                        # both admitted + decoding
        sup.cancel(dead)                   # acknowledged: in the inbox
        sup._mark_dead(RuntimeError("chaos"))   # dies before next pump
        sup.state = "dead"
        assert sup.restart()
        while sup.poll(keep) is None and sup.pump(1):
            pass
        assert sup.poll(keep).status == "done"
        res = sup.poll(dead)
        assert res is not None and res.status == "cancelled", res

    def test_cancel_pending_at_failover_is_not_resurrected(self, toy,
                                                           rng):
        """The drain-side sibling of the restart regression, found by
        the APX304 protocol model check (`apex1_tpu.lint.protocols`):
        an acknowledged cancel in the inbox when the replica fails
        must not be forwarded to the survivor by `drain_inflight` —
        the caller was already told the work is cancelled."""
        from apex1_tpu.serving import ReplicaSupervisor
        make_engine = _make_engine_factory(toy)
        sup = ReplicaSupervisor(make_engine, 0,
                                config=ReplicaConfig(watchdog_s=60.0))
        keep = sup.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=6)
        dead = sup.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=20)
        sup.pump(2)
        sup.cancel(dead)                   # acknowledged: in the inbox
        sup._mark_dead(RuntimeError("chaos"))
        subs = sup.drain_inflight()
        assert [s.req_id for s in subs] == [keep]
        res = sup.poll(dead)
        assert res is not None and res.status == "cancelled", res
        assert "failover" in res.reason

    def test_failover_never_resurrects_a_cancelled_request(self, toy,
                                                           rng):
        """End to end: cancel acknowledged on a replica that then
        fails its restart budget — the failover reroute must exclude
        the cancelled id (a "done" result for it would be resurrected
        work) while every survivor still comes out token-identical."""
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0,
                                                 max_restarts=0)))
        rids = _submit_mix(front, rng, 6, new=12)
        front.pump(2)                      # all admitted + decoding
        rep = front.replicas[0]
        victim = sorted(rep._inflight)[0]
        assert front.cancel(victim)        # acked, sits in the inbox
        rep._mark_dead(RuntimeError("chaos"))
        front.run_until_drained(timeout_s=60.0)
        assert front.replica_states() == ["failed", "alive"]
        res = front.poll(victim)
        assert res is not None and res.status == "cancelled", res
        fo = [t for t in front.metrics.transitions
              if t["event"] == "failover"]
        assert len(fo) == 1 and victim not in fo[0]["rerouted"]
        others = [r for r in rids if r != victim]
        want = _reference(make_engine, front, others)
        for rid in others:
            r = front.poll(rid)
            assert r is not None and r.status == "done", (rid, r)
            np.testing.assert_array_equal(r.tokens, want[rid])

    def test_infeasible_guaranteed_does_not_displace_sheddable(
            self, toy, rng):
        """Feasibility is checked BEFORE displacement: a guaranteed
        admission that will be rejected as infeasible must not first
        shed an innocent victim for nothing."""
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            # overload ladder disabled (thresholds unreachable): this
            # test isolates the DISPLACEMENT path at full capacity
            FrontendConfig(n_replicas=1, capacity_per_replica=2,
                           hedge_after_s=None, enter_shed=99.0,
                           enter_degraded=99.0,
                           replica=ReplicaConfig(watchdog_s=60.0)))
        warm = front.submit(rng.integers(0, VOCAB, (4,)),
                            max_new_tokens=4)
        front.run_until_drained(timeout_s=60.0)   # seeds step_ewma
        assert front.poll(warm).status == "done"
        s1 = front.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=6, qos="sheddable")
        front.submit(rng.integers(0, VOCAB, (4,)),
                     max_new_tokens=6, qos="sheddable")
        with pytest.raises(Backpressure, match="feasibly"):
            front.submit(rng.integers(0, VOCAB, (3,)),
                         max_new_tokens=5000, qos="guaranteed",
                         deadline=time.monotonic() + 1e-5)
        assert front.summary()["counters"]["sheds"] == 0
        front.run_until_drained(timeout_s=60.0)
        assert front.poll(s1).status == "done"    # nobody was shed


class TestDeadlineFeasibilityRouting:
    def test_infeasible_deadline_rejected_at_the_door(self, toy, rng):
        """Once the router has timing history, a deadline no replica
        can plausibly meet is rejected with retry_after_s=0 (retrying
        won't help) instead of admitted to fail."""
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=1, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0)))
        warm = front.submit(rng.integers(0, VOCAB, (4,)),
                            max_new_tokens=4)
        front.run_until_drained(timeout_s=60.0)
        assert front.poll(warm).status == "done"
        assert front.replicas[0].step_ewma > 0.0
        with pytest.raises(Backpressure) as ei:
            front.submit(rng.integers(0, VOCAB, (4,)),
                         max_new_tokens=5000,
                         deadline=time.monotonic() + 1e-5)
        assert "feasibly" in ei.value.reason
        assert ei.value.retry_after_s == 0.0
        # a generous deadline on the same replica is admitted
        ok = front.submit(rng.integers(0, VOCAB, (4,)),
                          max_new_tokens=4,
                          deadline=time.monotonic() + 60.0)
        front.run_until_drained(timeout_s=60.0)
        assert front.poll(ok).status == "done"


class TestThreadedFrontend:
    def test_threaded_replicas_drain_and_match_reference(self, toy,
                                                         rng):
        """The production drive mode: threaded serve loops under the
        main-thread supervision tick. Streams still match the
        uninterrupted reference bit-for-bit (per-request seeds make
        parity independent of thread interleaving)."""
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0))
        ).start()
        try:
            rids = _submit_mix(front, rng, 6)
            front.run_until_drained(timeout_s=60.0)
            want = _reference(make_engine, front, rids)
            for rid in rids:
                res = front.poll(rid)
                assert res.status == "done"
                np.testing.assert_array_equal(res.tokens, want[rid])
        finally:
            front.stop()
        assert all(s in ("stopped", "alive")
                   for s in front.replica_states())


class TestChaosScheduleCompose:
    def test_composed_faults_all_fire(self, toy, rng):
        make_engine = _make_engine_factory(toy)
        kill = ReplicaKill(replica=0, at_step=4)
        slow = SlowReplica(1, delay_s=0.002, from_step=0, to_step=6)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=8,
                           hedge_after_s=None,
                           replica=ReplicaConfig(watchdog_s=60.0)),
            fault=ChaosSchedule([kill, slow]))
        rids = _submit_mix(front, rng, 5)
        front.run_until_drained(timeout_s=60.0)
        assert kill.fired == 1
        assert all(front.poll(r).status == "done" for r in rids)


class TestSteadyStateInt8Tier:
    def test_frontend_cache_dtype_builds_every_replica_on_the_tier(
            self, toy, rng):
        """ISSUE 15: FrontendConfig.cache_dtype is the STEADY-STATE
        capacity tier — every replica engine's pool rides it from the
        first build (not just degraded restarts), at a quarter of the
        fp32 pool bytes, with streams token-identical to an fp32
        reference engine (toy cache values are exact in int8)."""
        import jax
        import jax.numpy as jnp
        make_engine = _make_engine_factory(toy)
        front = ServingFrontend(
            make_engine,
            FrontendConfig(n_replicas=2, capacity_per_replica=6,
                           seed=7, hedge_after_s=None,
                           cache_dtype=jnp.int8,
                           replica=ReplicaConfig(watchdog_s=60.0)))
        rids = _submit_mix(front, rng, 4, new=8)
        front.run_until_drained(timeout_s=60.0)
        want = _reference(make_engine, front, rids)
        for rid in rids:
            res = front.poll(rid)
            assert res.status == "done"
            np.testing.assert_array_equal(res.tokens, want[rid])
        for rep in front.replicas:
            leaves = jax.tree_util.tree_leaves(rep.engine.kv.cache)
            assert all(x.dtype == jnp.int8 for x in leaves)
            # the capacity arithmetic the tier buys: 1/4 the fp32 pool
            ref = make_engine()
            assert rep.engine.kv.pool_bytes() * 4 \
                == ref.kv.pool_bytes()
        # degraded restarts still take precedence over the steady tier
        # (DegradeProfile.cache_dtype wins while degraded) — pinned by
        # TestOverloadDrill::test_degraded_restart_rides_quantized_kv
