"""MoE + expert parallelism tests (beyond-reference: SURVEY §2.6 marks EP
[absent] in apex). Gold = per-token python routing; the shard_map
all-to-all form must match the single-device dense-dispatch form."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.transformer import moe as moe_lib
from apex1_tpu.transformer.moe import MoEConfig, MoEMLP


def _gold_moe(x2, params, cfg, act=jax.nn.gelu):
    """Per-token loop: top-k, renormalized gates, no capacity drops."""
    probs = jax.nn.softmax(
        np.asarray(x2, np.float32) @ np.asarray(params["router"]), axis=-1)
    out = np.zeros_like(np.asarray(x2, np.float32))
    for t in range(x2.shape[0]):
        idx = np.argsort(-probs[t])[:cfg.top_k]
        gates = probs[t, idx] / probs[t, idx].sum()
        for g, e in zip(gates, idx):
            h = np.asarray(act(jnp.asarray(
                np.asarray(x2, np.float32)[t] @ params["w1"][e])))
            out[t] += g * (h @ params["w2"][e])
    return out


class TestRouter:
    def test_dispatch_combine_shapes_and_weights(self, rng):
        cfg = MoEConfig(num_experts=4, top_k=2, capacity_factor=8.0,
                        hidden_size=8)
        x = jnp.asarray(rng.normal(size=(10, 8)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        dispatch, combine, aux = moe_lib.router(x, wg, cfg)
        T, E, C = dispatch.shape
        assert (T, E) == (10, 4)
        # every token dispatched to exactly top_k slots (capacity ample)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(dispatch, axis=(1, 2))), 2.0)
        # combine weights per token sum to 1 (renormalized top-k)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(combine, axis=(1, 2))), 1.0, rtol=1e-5)
        # a slot holds at most one token
        assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
        assert np.isfinite(float(aux))

    def test_capacity_drops(self, rng):
        cfg = MoEConfig(num_experts=2, top_k=1, capacity_factor=0.5,
                        hidden_size=4)
        x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        wg = jnp.zeros((4, 2), jnp.float32)  # ties -> all to expert 0
        dispatch, combine, aux = moe_lib.router(x, wg, cfg)
        C = dispatch.shape[-1]
        assert C == 2  # ceil-ish of 0.5 * 8 / 2
        # only C tokens make it; the rest dropped
        assert float(jnp.sum(dispatch)) == C

    def test_aux_loss_uniform_router(self, rng):
        cfg = MoEConfig(num_experts=4, top_k=1, hidden_size=8,
                        aux_loss_weight=1.0)
        x = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        _, _, aux = moe_lib.router(x, jnp.zeros((8, 4)), cfg)
        # uniform probs: E * sum(f_e * 1/E) = 1 regardless of assignment
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


class TestMoEMLP:
    def test_matches_per_token_gold(self, rng):
        cfg = MoEConfig(num_experts=4, top_k=2, capacity_factor=16.0,
                        hidden_size=8, ffn_size=16)
        model = MoEMLP(cfg)
        x = jnp.asarray(rng.normal(size=(2, 6, 8)), jnp.float32)
        params = model.init(jax.random.key(0), x)["params"]
        y, aux = model.apply({"params": params}, x)
        gold = _gold_moe(np.asarray(x).reshape(-1, 8),
                         jax.tree.map(np.asarray, params), cfg)
        np.testing.assert_allclose(np.asarray(y).reshape(-1, 8), gold,
                                   rtol=2e-4, atol=2e-5)

    def test_param_specs(self, rng):
        cfg = MoEConfig(num_experts=4, hidden_size=8, ffn_size=16)
        model = MoEMLP(cfg)
        x = jnp.ones((1, 4, 8), jnp.float32)
        params = model.init(jax.random.key(0), x)["params"]
        specs = moe_lib.param_specs(params)
        from jax.sharding import PartitionSpec as P
        assert specs["w1"] == P("ep", None, None)
        assert specs["router"] == P()

    def test_grads_flow(self, rng):
        cfg = MoEConfig(num_experts=2, top_k=1, capacity_factor=4.0,
                        hidden_size=8, ffn_size=16)
        model = MoEMLP(cfg)
        x = jnp.asarray(rng.normal(size=(1, 8, 8)), jnp.float32)
        params = model.init(jax.random.key(0), x)["params"]

        def loss(p):
            y, aux = model.apply({"params": p}, x)
            return jnp.sum(jnp.square(y)) + aux

        g = jax.grad(loss)(params)
        # router learns through both combine weights AND the aux loss
        assert float(jnp.max(jnp.abs(g["router"]))) > 0
        for leaf in jax.tree.leaves(g):
            assert np.all(np.isfinite(leaf))


class TestExpertParallel:
    def test_shard_map_matches_dense(self, rng, devices):
        """all_to_all EP dataflow over ep=4 == single-device dense MoE on
        the same tokens/weights (ample capacity so drops can't differ —
        local capacity is computed from the local token count)."""
        cfg = MoEConfig(num_experts=4, top_k=2, capacity_factor=32.0,
                        hidden_size=8, ffn_size=16)
        mesh = make_mesh(ep=4, dp=1, devices=devices[:4])
        T, H = 16, 8
        x = jnp.asarray(rng.normal(size=(T, H)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(H, 4)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(4, H, 16)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(4, 16, H)) * 0.1, jnp.float32)

        from jax.sharding import PartitionSpec as P

        def f(x, wg, w1, w2):
            y, aux = moe_lib.moe_shard_map_apply(x, wg, w1, w2, cfg)
            return y, jax.lax.pmean(aux, "ep")

        y_ep, aux_ep = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=(P("ep"), P()),
            check_vma=False))(x, wg, w1, w2)

        # dense single-device reference with identical weights
        cfg_dense = MoEConfig(num_experts=4, top_k=2, capacity_factor=32.0,
                              hidden_size=8, ffn_size=16)
        dispatch, combine, _ = moe_lib.router(x, wg, cfg_dense)
        xe = jnp.einsum("tec,th->ech", dispatch, x)
        h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", xe, w1))
        ye = jnp.einsum("ecf,efh->ech", h, w2)
        y_ref = jnp.einsum("tec,ech->th", combine, ye)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                                   rtol=2e-4, atol=2e-5)
        assert np.isfinite(float(aux_ep))

    def test_gspmd_sharded_params_match(self, rng, devices):
        """GSPMD form: expert weights sharded over ep -> same outputs."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        cfg = MoEConfig(num_experts=8, top_k=2, capacity_factor=8.0,
                        hidden_size=8, ffn_size=16)
        model = MoEMLP(cfg)
        x = jnp.asarray(rng.normal(size=(2, 8, 8)), jnp.float32)
        params = model.init(jax.random.key(0), x)["params"]
        ref, _ = jax.jit(lambda p: model.apply({"params": p}, x))(params)
        mesh = make_mesh(ep=8, dp=1, devices=devices[:8])
        specs = moe_lib.param_specs(params)
        sharded = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda v: isinstance(v, P)))
        got, _ = jax.jit(lambda p: model.apply({"params": p}, x))(sharded)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestLlamaMoE:
    @pytest.mark.slow  # training loop; MoE math covered by parity tests
    def test_moe_llama_trains(self, rng):
        """Llama with every-2nd-block MoE: forward finite, aux loss joins
        the objective, grads reach router + experts + dense layers."""
        import dataclasses

        from apex1_tpu.models.llama import Llama, LlamaConfig, llama_loss_fn
        cfg = dataclasses.replace(LlamaConfig.tiny(), moe_every=2,
                                  num_experts=4, moe_top_k=2,
                                  moe_capacity_factor=4.0)
        model = Llama(cfg)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)),
                             jnp.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        assert "moe" in params["layer1"] and "moe" not in params["layer0"]
        loss_fn = llama_loss_fn(model)
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        assert np.isfinite(float(loss))
        assert float(jnp.max(jnp.abs(
            grads["layer1"]["moe"]["router"]))) > 0
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(leaf))

    def test_moe_llama_param_specs(self, rng):
        import dataclasses

        from jax.sharding import PartitionSpec as P

        from apex1_tpu.models.llama import Llama, LlamaConfig, param_specs
        cfg = dataclasses.replace(LlamaConfig.tiny(), moe_every=2,
                                  num_experts=4)
        model = Llama(cfg)
        tokens = jnp.ones((1, 8), jnp.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        specs = param_specs(params)
        assert specs["layer1"]["moe"]["w1"] == P("ep", None, None)
        assert specs["layer1"]["moe"]["router"] == P()
        assert specs["layer0"]["w_gate"] == P(None, "tp")


def test_router_token_mask_excludes_padding(rng):
    """Masked (padding) tokens claim no capacity slots and don't steer
    the load-balance statistics."""
    cfg = MoEConfig(num_experts=2, top_k=1, capacity_factor=1.0,
                    hidden_size=4, aux_loss_weight=1.0)
    x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
    wg = jnp.zeros((4, 2), jnp.float32)  # ties: all to expert 0
    mask = jnp.asarray([1, 1, 1, 1, 0, 0, 0, 0], bool)
    dispatch, combine, aux = moe_lib.router(x, wg, cfg, mask)
    # padding rows have zero dispatch; real tokens keep their slots
    np.testing.assert_allclose(
        np.asarray(jnp.sum(dispatch[4:], axis=(1, 2))), 0.0)
    assert float(jnp.sum(dispatch[:4])) == 4.0  # capacity C=4 fits all
    # aux over valid tokens only: uniform probs -> exactly 1.0
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)
