"""Unit tests for the driver-facing perf tooling.

Two suites: bench.py's unreachable-backend fallback (the JSON line
must always emit and, when banked on-silicon records exist in
perf_results/, carry a `best_banked` pointer — bench.py::_last_banked,
pinned against synthetic queue logs including the malformed lines a
tunnel death can leave behind), and tools/measured_vs_predicted.py's
roofline-scoring join (its rows feed BASELINE.md and the judge's perf
assessment).
"""

import importlib.util
import json
import os
import pathlib

import pytest

_REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def bench_mod():
    spec = importlib.util.spec_from_file_location("_bench_for_test",
                                                  _REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _results(tmp_path, logs):
    """Write a synthetic perf_results dir."""
    res = tmp_path / "perf_results"
    res.mkdir()
    for name, lines in logs.items():
        (res / name).write_text("\n".join(
            json.dumps(x) if isinstance(x, dict) else x for x in lines)
            + "\n")
    return str(res)


class TestLastBanked:
    def test_picks_best_across_logs(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_gpt2.log": [
                {"metric": "m [tpu]", "value": 100.0, "unit": "u"}],
            "bench_gpt2_b24.log": [
                {"metric": "m [tpu]", "value": 200.0, "unit": "u"}],
        })
        rec = bench_mod._last_banked("gpt2", res)
        assert rec["value"] == 200.0
        assert rec["source_log"].endswith("bench_gpt2_b24.log")
        # the record names its own selection rule (the key is
        # `best_banked`, NOT "most recent at the standard shape")
        assert rec["selection"] == "max across queue logs"

    def test_requires_tpu_backend_tag(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_bert.log": [
                {"metric": "m [cpu]", "value": 5.0, "unit": "u"},
                {"metric": "m [unreachable]", "value": 0.0, "unit": "u"}],
        })
        assert bench_mod._last_banked("bert", res) is None

    def test_skips_zero_nonnumeric_and_garbage(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_t5.log": [
                "WARNING: some init noise",
                {"metric": "m [tpu]", "value": 0.0, "unit": "u"},
                {"metric": "m [tpu]", "value": "999999", "unit": "u"},
                '{"bad": }',
                '{"metric": "m [tpu]", "value": NaN, "unit": "u"}',
                '{"metric": "m [tpu]", "value": true, "unit": "u"}',
                {"metric": "m [tpu]", "value": 42.0, "unit": "u"}],
        })
        rec = bench_mod._last_banked("t5", res)
        assert rec["value"] == 42.0

    def test_missing_files_and_unknown_config(self, bench_mod, tmp_path):
        res = _results(tmp_path, {})
        assert bench_mod._last_banked("gpt2", res) is None
        assert bench_mod._last_banked("no_such_config", res) is None

    def test_real_repo_logs_if_present(self, bench_mod):
        """The shipping perf_results/ must resolve without error (value
        may be None on a fresh clone with no banked logs)."""
        rec = bench_mod._last_banked("gpt2")
        if rec is not None:
            assert rec["value"] > 0
            assert "[tpu]" in rec["metric"]

    def test_every_bench_config_has_log_mapping(self, bench_mod):
        assert set(bench_mod._BANKED_LOGS) == set(bench_mod.BENCHES)


@pytest.fixture(scope="module")
def mvp_mod():
    spec = importlib.util.spec_from_file_location(
        "_mvp_for_test", _REPO / "tools" / "measured_vs_predicted.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCrashSafeBanking:
    def test_emit_banks_record_atomically(self, bench_mod, tmp_path,
                                          capsys):
        """--out satellite: the record lands at out_path via temp-file
        + atomic rename (no .tmp debris), nested dirs are created, and
        stdout still carries the driver's JSON line."""
        rec = {"metric": "m [tpu]", "value": 1.5}
        out = tmp_path / "sweep" / "gpt2.json"
        bench_mod._emit(rec, str(out))
        assert json.loads(capsys.readouterr().out) == rec
        assert json.loads(out.read_text()) == rec
        assert os.listdir(out.parent) == ["gpt2.json"]

    def test_emit_overwrites_previous_record(self, bench_mod, tmp_path):
        out = tmp_path / "r.json"
        bench_mod._emit({"value": 1}, str(out))
        bench_mod._emit({"value": 2}, str(out))
        assert json.loads(out.read_text()) == {"value": 2}

    def test_emit_banking_failure_never_eats_the_record(self, bench_mod,
                                                        tmp_path,
                                                        capsys):
        """Banking is best-effort: an unwritable out_path warns on
        stderr but the stdout line (the driver contract) still prints."""
        target = tmp_path / "f"
        target.write_text("not a dir")
        rec = {"value": 3}
        bench_mod._emit(rec, str(target / "x.json"))
        captured = capsys.readouterr()
        assert json.loads(captured.out) == rec
        assert "could not bank" in captured.err

    def test_try_resume_falls_back_to_fresh_on_junk_dir(self, bench_mod,
                                                        tmp_path,
                                                        capsys):
        """--resume auto must measure, not die, on a stale/foreign
        checkpoint dir."""
        template = {"w": [1, 2, 3]}
        (tmp_path / "step_00000001").mkdir()   # uncommitted debris
        state, resumed = bench_mod._try_resume(str(tmp_path), template)
        assert state is template and resumed is None
        assert "starting fresh" in capsys.readouterr().err


class TestMeasuredVsPredicted:
    """The roofline-scoring artifact generator: its rows feed BASELINE.md
    and the judge's perf assessment, so pin the join arithmetic."""

    def _run(self, mvp_mod, tmp_path, logs, monkeypatch):
        res = pathlib.Path(_results(tmp_path, logs))
        pred = {"topology": "v5e:2x2", "kernels": [], "steps": [
            {"name": "gpt2", "metric": "m", "unit": "tokens/sec/chip",
             "proxy": 145000.0, "units_per_step": 16384,
             # 19.7 TF, 81.9 GB -> v5e roofline: max(0.1s, 0.1s) = 100ms
             "flops": 19.7e12, "bytes": 81.9e9,
             "flops_pallas_visible": 1e12, "mfu_correction": 2.0,
             "temp_gib": 1.0, "args_gib": 1.0}]}
        (res / "predicted_r5.json").write_text(json.dumps(pred))
        out = tmp_path / "out.md"
        monkeypatch.setattr(
            "sys.argv",
            ["mvp", "--results", str(res), "--out", str(out)])
        mvp_mod.main()
        return out.read_text()

    def test_join_arithmetic(self, mvp_mod, tmp_path, monkeypatch):
        text = self._run(mvp_mod, tmp_path, {
            "bench_gpt2.log": [{
                "metric": "m [tpu]", "value": 81920.0,
                "unit": "tokens/sec/chip", "vs_baseline": 0.565,
                "step_ms": 200.0}],
        }, monkeypatch)
        row = [l for l in text.splitlines() if l.startswith("| gpt2")][0]
        cells = [c.strip() for c in row.split("|")]
        # pred ms: max(19.7e12/197e12, 81.9e9/819e9) = 0.1 s
        assert cells[6] == "100.0"
        # roofline frac: 100 / 200 = 0.50
        assert cells[7] == "0.50"
        # true MFU: 19.7e12 / 0.2 / 197e12 = 0.5
        assert cells[8] == "0.500"
        # HBM GB/s: 81.9e9 / 0.2 / 1e9 = 410
        assert cells[9] == "410"

    def test_missing_and_failed_rows_render(self, mvp_mod, tmp_path,
                                            monkeypatch):
        text = self._run(mvp_mod, tmp_path, {
            "bench_gpt2.log": [{"metric": "m [unreachable]",
                                "value": 0.0, "unit": "u"}],
        }, monkeypatch)
        # a 0.0 (failed) record and absent logs both render as no-result
        gpt2 = [l for l in text.splitlines() if l.startswith("| gpt2")]
        assert gpt2 and "(no result)" in gpt2[0]
        bert = [l for l in text.splitlines() if l.startswith("| bert ")]
        assert bert and "(no result)" in bert[0]


class TestRooflineRatio:
    """bench.py's roofline surface: `predicted` + `roofline_ratio` ride
    every record with a real value (incl. the best_banked pointer), from
    the newest banked predicted_*.json priced at the current chip."""

    def _predictions(self, tmp_path, flops=197e12, nbytes=819e9,
                     units=16384):
        res = tmp_path / "perf_results"
        res.mkdir(exist_ok=True)
        (res / "predicted_r5.json").write_text(json.dumps({
            "steps": [{"name": "gpt2", "units_per_step": units,
                       "flops": flops, "bytes": nbytes}]}))
        return str(res)

    def test_predicted_rate_roofline_math(self, bench_mod, tmp_path):
        res = self._predictions(tmp_path)
        # off-TPU capability defaults to the v5e row (197 TF, 819 GB/s):
        # t_pred = max(1.0, 1.0) = 1 s -> units/sec == units_per_step
        assert bench_mod._predicted_rate("gpt2", res) == \
            pytest.approx(16384.0)

    def test_attach_ratio(self, bench_mod, tmp_path):
        res = self._predictions(tmp_path)
        rec = bench_mod._attach_roofline(
            {"metric": "m [tpu]", "value": 8192.0}, "gpt2", res)
        assert rec["predicted"] == pytest.approx(16384.0)
        assert rec["roofline_ratio"] == pytest.approx(0.5)

    def test_no_ratio_on_zero_value_or_missing_table(self, bench_mod,
                                                     tmp_path):
        res = self._predictions(tmp_path)
        rec = bench_mod._attach_roofline({"value": 0.0}, "gpt2", res)
        assert "roofline_ratio" not in rec and "predicted" not in rec
        # unknown config / empty results dir: record passes through
        assert bench_mod._attach_roofline(
            {"value": 5.0}, "nope", res) == {"value": 5.0}
        empty = tmp_path / "empty"
        empty.mkdir()
        assert bench_mod._predicted_rate("gpt2", str(empty)) is None


    def test_no_ratio_on_cpu_smoke_records(self, bench_mod, tmp_path):
        # cpu smoke runs measure tiny auto-shrunk shapes — a ratio vs
        # the accelerator-shape prediction would be noise
        res = self._predictions(tmp_path)
        rec = bench_mod._attach_roofline(
            {"metric": "m [cpu]", "value": 9.0}, "gpt2", res)
        assert "roofline_ratio" not in rec


    def test_newest_prediction_table_by_mtime(self, bench_mod,
                                              tmp_path):
        # lexicographic order would pick r9 over r10; mtime must win
        res = tmp_path / "perf_results"
        res.mkdir()
        old = res / "predicted_r9.json"
        new = res / "predicted_r10.json"
        old.write_text(json.dumps({"steps": [
            {"name": "gpt2", "units_per_step": 1,
             "flops": 197e12, "bytes": 1.0}]}))
        new.write_text(json.dumps({"steps": [
            {"name": "gpt2", "units_per_step": 2,
             "flops": 197e12, "bytes": 1.0}]}))
        os.utime(old, (1_000_000, 1_000_000))
        os.utime(new, (2_000_000, 2_000_000))
        assert bench_mod._predicted_rate("gpt2", str(res)) == \
            pytest.approx(2.0)

    def test_garbage_prediction_file_never_raises(self, bench_mod,
                                                  tmp_path):
        res = tmp_path / "perf_results"
        res.mkdir()
        (res / "predicted_r9.json").write_text("{broken")
        rec = bench_mod._attach_roofline({"value": 7.0}, "gpt2",
                                         str(res))
        assert rec == {"value": 7.0}


class TestCommsTerm:
    """The roofline ICI comms term: exposed (non-overlapped) bytes ADD
    transfer time to the prediction, so `roofline_ratio` prices the
    overlap layer's win instead of crediting serialized collectives as
    free; and the analytic comms table itself is well-formed."""

    def test_predicted_rate_prices_exposed_ici_bytes(self, bench_mod,
                                                     tmp_path):
        res = tmp_path / "perf_results"
        res.mkdir()
        # off-TPU capability = v5e row: 197 TF, 819 GB/s, ici link
        # 200/(2*2) = 50 GB/s. base t = 1 s; exposed 50 GB -> +1 s.
        (res / "predicted_r9.json").write_text(json.dumps({"steps": [
            {"name": "gpt2", "units_per_step": 16384,
             "flops": 197e12, "bytes": 819e9,
             "ici_exposed_bytes": 50e9}]}))
        assert bench_mod._predicted_rate("gpt2", str(res)) == \
            pytest.approx(16384.0 / 2.0)

    def test_zero_ici_field_changes_nothing(self, bench_mod, tmp_path):
        res = tmp_path / "perf_results"
        res.mkdir()
        (res / "predicted_r9.json").write_text(json.dumps({"steps": [
            {"name": "gpt2", "units_per_step": 16384,
             "flops": 197e12, "bytes": 819e9,
             "ici_bytes": 0.0, "ici_exposed_bytes": 0.0}]}))
        assert bench_mod._predicted_rate("gpt2", str(res)) == \
            pytest.approx(16384.0)

    def test_ici_link_rate(self):
        from apex1_tpu.core.capability import ici_link_gbps
        # v5e: 200 GB/s aggregate over a 2D torus's 4 links
        assert ici_link_gbps("v5e") == pytest.approx(50.0)
        assert ici_link_gbps("v5p") == pytest.approx(600.0 / 6.0)

    def test_predict_comms_rows(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_pp_for_test", _REPO / "tools" / "predict_perf.py")
        pp = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pp)
        rows = pp.predict_comms()
        assert len(rows) == 8  # {v5e,v5p} x {cp4,cp8} x {fwd,bwd}
        for r in rows:
            assert r["exposed_bytes_serial"] == r["ici_bytes"]
            assert 0.0 <= r["exposed_bytes_overlap"] <= r["ici_bytes"]
        # at the 16k shape the attend covers the hop: overlap hides all
        v5e_fwd4 = next(r for r in rows if r["generation"] == "v5e"
                        and r["cp"] == 4 and r["phase"] == "fwd")
        assert v5e_fwd4["exposed_bytes_overlap"] == 0.0
        assert v5e_fwd4["t_serial_ms"] > 0.1
