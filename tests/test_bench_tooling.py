"""Unit tests for bench.py's driver-facing fallback machinery.

The unreachable-backend JSON line must always emit and, when banked
on-silicon records exist in perf_results/, carry a `last_measured`
pointer (bench.py::_last_banked). These tests pin the lookup's
contract against synthetic queue logs — including the malformed lines
a tunnel death can leave behind.
"""

import importlib.util
import json
import pathlib

import pytest

_REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def bench_mod():
    spec = importlib.util.spec_from_file_location("_bench_for_test",
                                                  _REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _results(tmp_path, logs):
    """Write a synthetic perf_results dir."""
    res = tmp_path / "perf_results"
    res.mkdir()
    for name, lines in logs.items():
        (res / name).write_text("\n".join(
            json.dumps(x) if isinstance(x, dict) else x for x in lines)
            + "\n")
    return str(res)


class TestLastBanked:
    def test_picks_best_across_logs(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_gpt2.log": [
                {"metric": "m [tpu]", "value": 100.0, "unit": "u"}],
            "bench_gpt2_b24.log": [
                {"metric": "m [tpu]", "value": 200.0, "unit": "u"}],
        })
        rec = bench_mod._last_banked("gpt2", res)
        assert rec["value"] == 200.0
        assert rec["source_log"].endswith("bench_gpt2_b24.log")

    def test_requires_tpu_backend_tag(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_bert.log": [
                {"metric": "m [cpu]", "value": 5.0, "unit": "u"},
                {"metric": "m [unreachable]", "value": 0.0, "unit": "u"}],
        })
        assert bench_mod._last_banked("bert", res) is None

    def test_skips_zero_nonnumeric_and_garbage(self, bench_mod, tmp_path):
        res = _results(tmp_path, {
            "bench_t5.log": [
                "WARNING: some init noise",
                {"metric": "m [tpu]", "value": 0.0, "unit": "u"},
                {"metric": "m [tpu]", "value": "999999", "unit": "u"},
                '{"bad": }',
                '{"metric": "m [tpu]", "value": NaN, "unit": "u"}',
                '{"metric": "m [tpu]", "value": true, "unit": "u"}',
                {"metric": "m [tpu]", "value": 42.0, "unit": "u"}],
        })
        rec = bench_mod._last_banked("t5", res)
        assert rec["value"] == 42.0

    def test_missing_files_and_unknown_config(self, bench_mod, tmp_path):
        res = _results(tmp_path, {})
        assert bench_mod._last_banked("gpt2", res) is None
        assert bench_mod._last_banked("no_such_config", res) is None

    def test_real_repo_logs_if_present(self, bench_mod):
        """The shipping perf_results/ must resolve without error (value
        may be None on a fresh clone with no banked logs)."""
        rec = bench_mod._last_banked("gpt2")
        if rec is not None:
            assert rec["value"] > 0
            assert "[tpu]" in rec["metric"]

    def test_every_bench_config_has_log_mapping(self, bench_mod):
        assert set(bench_mod._BANKED_LOGS) == set(bench_mod.BENCHES)
