"""DP services tests — ≙ ``tests/distributed/{DDP,synced_batchnorm}`` and
``apex/contrib/test/optimizers`` (DistributedFusedAdam): grad sync semantics,
SyncBN single-vs-multi-replica parity, ZeRO-sharded Adam vs unsharded gold."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu import parallel
from apex1_tpu.optim import FusedAdam


@pytest.fixture()
def mesh(devices):
    return make_mesh(dp=8)


@pytest.fixture()
def fsdp_mesh(devices):
    return make_mesh(fsdp=8)


def smap(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


class TestDDP:
    def test_allreduce_grads_is_mean(self, mesh, rng):
        g = jnp.asarray(np.arange(8, dtype=np.float32).reshape(8, 1))

        def f(g):
            return parallel.allreduce_grads({"w": g},
                                            axis_names=("dp",))["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        np.testing.assert_allclose(np.asarray(out),
                                   np.full((8, 1), 3.5), rtol=1e-6)

    def test_predivide_factor_net_mean(self, mesh, rng):
        g = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)

        def f(g):
            return parallel.allreduce_grads(
                {"w": g}, axis_names=("dp",),
                gradient_predivide_factor=4.0)["w"]

        out = smap(mesh, f, P("dp"), P("dp"))(g)
        np.testing.assert_allclose(
            np.asarray(out),
            np.broadcast_to(np.asarray(g).mean(0), (8, 4)), rtol=1e-5)

    def test_ddp_wrapper_end_to_end(self, mesh, rng):
        # per-replica batches; DDP grads == full-batch grads
        x = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(4, 1)) * 0.3, jnp.float32)

        def loss_fn(w, xb):
            return jnp.mean((xb @ w) ** 2)

        ddp = parallel.DistributedDataParallel(loss_fn, axis_names=("dp",))
        vg = ddp.value_and_grad()

        def f(w, xb):
            loss, grads = vg(w, xb)
            return jax.lax.pmean(loss, "dp"), grads

        loss, grads = smap(mesh, f, (P(), P("dp")), (P(), P()))(w, x)
        gold_loss, gold_grads = jax.value_and_grad(loss_fn)(w, x)
        np.testing.assert_allclose(float(loss), float(gold_loss), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads), np.asarray(gold_grads),
                                   rtol=1e-5, atol=1e-6)

    def test_broadcast_params(self, mesh, rng):
        # divergent per-rank params → rank-0 copy everywhere
        ps = jnp.asarray(rng.normal(size=(8, 3)), jnp.float32)

        def f(p):
            return parallel.broadcast_params(p, axis_names=("dp",))

        out = smap(mesh, f, P("dp"), P("dp"))(ps)
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(np.asarray(ps)[0], (8, 1)),
                                   rtol=1e-6)


class TestSyncBatchNorm:
    def test_stats_match_full_batch(self, mesh, rng):
        """The reference's canonical test: SyncBN over N replicas each with
        B/N samples == plain BN over the full batch."""
        x = jnp.asarray(rng.normal(size=(32, 6)) * 3 + 1, jnp.float32)
        bn = parallel.SyncBatchNorm(num_features=6, axis_name="dp", use_running_average=False)
        variables = bn.init(jax.random.PRNGKey(0), x[:4])

        def f(x_local):
            y, updates = bn.apply(variables, x_local,
                                  mutable=["batch_stats"])
            return y, updates["batch_stats"]["mean"]

        y, means = smap(mesh, f, P("dp"), (P("dp"), P()))(x)
        # gold: normalize with FULL-batch stats
        mu = np.asarray(x).mean(0)
        var = np.asarray(x).var(0)
        gold = (np.asarray(x) - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), gold, rtol=1e-4,
                                   atol=1e-5)
        # running mean updated with momentum towards full-batch mean
        np.testing.assert_allclose(np.asarray(means), 0.1 * mu, rtol=1e-4,
                                   atol=1e-5)

    def test_group_size_subgroups(self, mesh, rng):
        # group_size=4: two independent stat groups of 4 replicas
        x = jnp.asarray(rng.normal(size=(8, 2, 4)), jnp.float32)
        bn = parallel.SyncBatchNorm(num_features=4, axis_name="dp", use_running_average=False,
                                    group_size=4, track_running_stats=False)
        variables = bn.init(jax.random.PRNGKey(0), x[0])

        def f(x_local):
            return bn.apply(variables, x_local)

        y = smap(mesh, f, P("dp"), P("dp"))(x)
        xg = np.asarray(x)
        for g in range(2):
            grp = xg[g * 4:(g + 1) * 4].reshape(-1, 4)
            mu, var = grp.mean(0), grp.var(0)
            gold = (xg[g * 4:(g + 1) * 4] - mu) / np.sqrt(var + 1e-5)
            np.testing.assert_allclose(np.asarray(y)[g * 4:(g + 1) * 4],
                                       gold, rtol=1e-4, atol=1e-5)

    def test_grad_matches_full_batch_bn(self, mesh, rng):
        x = jnp.asarray(rng.normal(size=(16, 3)), jnp.float32)
        bn = parallel.SyncBatchNorm(num_features=3, axis_name="dp", use_running_average=False,
                                    track_running_stats=False)
        variables = bn.init(jax.random.PRNGKey(0), x[:2])

        def f(x_local):
            return jax.grad(lambda x: jnp.sum(
                bn.apply(variables, x) ** 2) / 16)(x_local)

        g = smap(mesh, f, P("dp"), P("dp"))(x)

        def gold_loss(x):
            mu = jnp.mean(x, 0)
            var = jnp.var(x, 0)
            return jnp.sum(((x - mu) / jnp.sqrt(var + 1e-5)) ** 2) / 16

        gold = jax.grad(gold_loss)(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gold),
                                   rtol=1e-3, atol=1e-5)

    def test_convert_syncbn_model(self):
        import flax.linen as nn

        class Net(nn.Module):
            bn: nn.Module = None

            @nn.compact
            def __call__(self, x):
                return self.bn(x)

        net = Net(bn=nn.BatchNorm(use_running_average=False))
        converted = parallel.convert_syncbn_model(net, axis_name=None)
        assert isinstance(converted.bn, parallel.SyncBatchNorm)

    def test_convert_recurses_into_containers(self):
        import flax.linen as nn

        class Net(nn.Module):
            layers: tuple = ()

            @nn.compact
            def __call__(self, x):
                for l in self.layers:
                    x = l(x)
                return x

        net = Net(layers=(nn.Dense(4), nn.BatchNorm(
            use_running_average=False), nn.Dense(4)))
        converted = parallel.convert_syncbn_model(net, axis_name=None)
        assert isinstance(converted.layers[1], parallel.SyncBatchNorm)
        assert isinstance(converted.layers[0], nn.Dense)

    def test_convert_preserves_bn_config(self):
        import flax.linen as nn

        bn = nn.BatchNorm(use_running_average=True, use_scale=True,
                          use_bias=False)
        sbn = parallel.convert_syncbn_model(bn, axis_name=None)
        assert sbn.use_running_average is True
        assert sbn.use_scale and not sbn.use_bias and sbn.affine

    def test_running_var_is_unbiased(self):
        # reference/torch convention: running_var stores var * n/(n-1)
        sbn = parallel.SyncBatchNorm(axis_name=None, momentum=1.0, use_running_average=False)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 3)),
                        jnp.float32)
        vs = sbn.init(jax.random.key(0), x)
        _, mut = sbn.apply(vs, x, mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["var"]),
            np.var(np.asarray(x), axis=0, ddof=1), rtol=1e-5)


class TestDistributedFusedAdam:
    @pytest.mark.slow
    def test_matches_unsharded_adam(self, fsdp_mesh, rng):
        params = {"w": jnp.asarray(rng.normal(size=(13, 5)), jnp.float32),
                  "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
        opt = parallel.distributed_fused_adam(1e-2, weight_decay=0.01)
        gold_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        gold_state = gold_opt.init(params)
        gold = params

        def one_step(params, state, grads):
            return opt.step(grads, state, params)

        from apex1_tpu.parallel.distributed_optimizer import (
            DistributedAdamState)
        state_spec = DistributedAdamState(step=P(),
                                          exp_avg_shard=P("fsdp"),
                                          exp_avg_sq_shard=P("fsdp"))

        def init_fn(params):
            return opt.init(params)

        state = smap(fsdp_mesh, init_fn, P(), state_spec)(params)
        step = smap(fsdp_mesh, one_step, (P(), state_spec, P()),
                    (P(), state_spec))
        for i in range(3):
            grads = jax.tree.map(
                lambda p: jnp.asarray(rng.normal(size=p.shape) * 0.1,
                                      jnp.float32), params)
            # replicate the mean-semantics: every rank has the same grads
            params, state = step(params, state, grads)
            gold, gold_state = gold_opt.step(grads, gold_state, gold)
            for k in ("w", "b"):
                np.testing.assert_allclose(np.asarray(params[k]),
                                           np.asarray(gold[k]),
                                           rtol=1e-5, atol=1e-6)

    def test_shard_opt_state_specs(self, rng):
        params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros(())}
        tx = FusedAdam(lr=1e-3)
        st = tx.init(params)
        specs = parallel.shard_opt_state_specs(st)
        assert specs.exp_avg["w"] == P("fsdp", None)
        assert specs.step == P()


class TestDistributedFusedLamb:
    def test_matches_full_lamb(self, rng, devices):
        """4-way flat-sharded LAMB == unsharded fused_lamb, per step —
        including the per-tensor trust ratios reconstructed across shard
        boundaries (reference DistributedFusedLAMB's guarantee)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from apex1_tpu.core.mesh import make_mesh
        from apex1_tpu.optim.fused_lamb import fused_lamb
        from apex1_tpu.parallel.distributed_optimizer import (
            distributed_fused_lamb)

        mesh = make_mesh(fsdp=4, dp=1, devices=devices[:4])
        params = {"w": jnp.asarray(rng.normal(size=(5, 7)), jnp.float32),
                  "b": jnp.asarray(rng.normal(size=(11,)), jnp.float32)}
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32),
            params)

        ref_tx = fused_lamb(1e-2, weight_decay=0.01)
        ref_state = ref_tx.init(params)
        dist = distributed_fused_lamb(1e-2, weight_decay=0.01,
                                      axis_name="fsdp")

        def run(params, grads):
            state = dist.init(params)
            p1, state = dist.step(grads, state, params)
            p2, state = dist.step(grads, state, p1)
            return p2

        sharded = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False))
        got = sharded(params, grads)

        import optax
        p_ref = params
        for _ in range(2):
            upd, ref_state = ref_tx.update(grads, ref_state, p_ref)
            p_ref = optax.apply_updates(p_ref, upd)
        for k in params:
            np.testing.assert_allclose(got[k], p_ref[k], rtol=1e-5,
                                       atol=1e-6)


class TestFsdpParamSpecs:
    """ZeRO-3 as sharding specs: params sharded over fsdp via
    `fsdp_param_specs` + opt state via `shard_opt_state_specs`, trained
    with pjit — must match the unsharded run exactly (GSPMD inserts the
    gather/reduce-scatter dataflow)."""

    def test_spec_shapes(self):
        params = {"big": jnp.zeros((64, 256)), "tall": jnp.zeros((4096,)),
                  "small": jnp.zeros((4, 4)), "s": jnp.zeros(())}
        specs = parallel.fsdp_param_specs(params, min_size=128)
        assert specs["big"] == P(None, "fsdp")   # largest dim sharded
        assert specs["tall"] == P("fsdp")
        assert specs["small"] == P()             # under min_size
        assert specs["s"] == P()
        # divisor steers to the largest DIVISIBLE dim (no shard padding)
        odd = {"emb": jnp.zeros((50257, 768))}
        assert parallel.fsdp_param_specs(odd, min_size=1)["emb"] == \
            P("fsdp", None)
        assert parallel.fsdp_param_specs(odd, min_size=1, divisor=8)[
            "emb"] == P(None, "fsdp")

    def test_pjit_training_matches_unsharded(self, fsdp_mesh, rng):
        from jax.sharding import NamedSharding

        from apex1_tpu.optim.fused_adam import fused_adam

        tx = fused_adam(1e-2)
        # w1's LARGEST dim is dim 1: exercises moment specs following the
        # param specs (dim-1 sharded) instead of blanket dim-0 sharding
        params = {"w1": jnp.asarray(rng.normal(size=(16, 64)), jnp.float32),
                  "w2": jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)}
        x = jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)

        def loss_fn(p):
            return jnp.mean(jnp.square(jnp.tanh(x @ p["w1"]) @ p["w2"] - y))

        def train(p, st):
            for _ in range(3):
                g = jax.grad(loss_fn)(p)
                up, st = tx.update(g, st, p)
                p = jax.tree.map(jnp.add, p, up)
            return p, loss_fn(p)

        ref_p, ref_l = jax.jit(train)(params, tx.init(params))

        pspecs = parallel.fsdp_param_specs(params, min_size=64)
        assert pspecs["w1"] == P(None, "fsdp")
        sspecs = parallel.shard_opt_state_specs(tx.init(params),
                                                axis="fsdp",
                                                param_specs=pspecs)
        # moments shard the SAME dim as their param (shard-local update)
        assert sspecs.exp_avg["w1"] == P(None, "fsdp")
        assert sspecs.step == P()
        shard = lambda t, s: jax.device_put(
            t, jax.tree.map(lambda sp: NamedSharding(fsdp_mesh, sp), s,
                            is_leaf=lambda v: isinstance(v, P)))
        p_sh = shard(params, pspecs)
        st_sh = shard(tx.init(params), sspecs)
        got_p, got_l = jax.jit(train)(p_sh, st_sh)
        np.testing.assert_allclose(float(got_l), float(ref_l), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(got_p), jax.tree.leaves(ref_p)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
