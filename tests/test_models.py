"""BERT + ResNet model tests (BASELINE configs 2/3 at tiny sizes) —
forward/loss/grad sanity, padding-mask semantics, SyncBN-in-model under a
dp mesh (≙ examples/imagenet amp+DDP+SyncBN flow)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.bert import (Bert, BertConfig, BertPretrain,
                                   bert_pretrain_loss_fn)
from apex1_tpu.models.resnet import ResNet, ResNetConfig


class TestBert:
    def _mk(self, **kw):
        cfg = BertConfig.tiny(**kw)
        model = BertPretrain(cfg)
        rng = np.random.default_rng(0)
        B, S = 2, 32
        batch = {
            "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)),
                                  jnp.int32),
            "mlm_labels": jnp.asarray(
                np.where(rng.random((B, S)) < 0.15,
                         rng.integers(0, cfg.vocab_size, (B, S)), -1),
                jnp.int32),
            "nsp_labels": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32),
        }
        params = model.init(jax.random.key(0), batch["tokens"])["params"]
        return cfg, model, batch, params

    def test_forward_shapes(self):
        cfg, model, batch, params = self._mk()
        mlm, nsp = model.apply({"params": params}, batch["tokens"])
        assert mlm.shape == (2, 32, cfg.vocab_size)
        assert nsp.shape == (2, 2)

    def test_loss_grads_finite(self):
        cfg, model, batch, params = self._mk()
        loss_fn = bert_pretrain_loss_fn(model)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        assert np.isfinite(float(loss))
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(leaf))

    def test_fused_head_matches_materialized(self):
        """fuse_head folds the decoder bias into the linear-CE kernel via
        the ones-column trick — loss and grads must match the
        materialized-logits gold (incl. wte and mlm_bias grads)."""
        cfg, model, batch, params = self._mk()
        # init gives mlm_bias == 0, which would test the bias fold only at
        # the one point where any scaling/rounding mistake vanishes — use
        # trained-checkpoint-magnitude values
        rng = np.random.default_rng(3)
        params = dict(params)
        params["mlm_bias"] = jnp.asarray(
            rng.normal(size=params["mlm_bias"].shape) * 2.0, jnp.float32)
        fused = bert_pretrain_loss_fn(model, fuse_head=True)
        gold = bert_pretrain_loss_fn(model, fuse_head=False)
        lf, gf = jax.value_and_grad(fused)(params, batch)
        lg, gg = jax.value_and_grad(gold)(params, batch)
        np.testing.assert_allclose(float(lf), float(lg), rtol=2e-5)
        for a, b, path in zip(jax.tree.leaves(gf), jax.tree.leaves(gg),
                              jax.tree_util.tree_flatten_with_path(gf)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
                err_msg=str(path[0]))

    def test_padding_does_not_leak(self):
        """Changing pad-token content must not change real-token outputs."""
        cfg = BertConfig.tiny()
        model = Bert(cfg)
        rng = np.random.default_rng(0)
        B, S, pad_from = 2, 32, 20
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)),
                             jnp.int32)
        mask = jnp.asarray(
            np.arange(S)[None, :] < pad_from, jnp.int32).repeat(B, 0)
        params = model.init(jax.random.key(0), tokens)["params"]
        seq1, _ = model.apply({"params": params}, tokens,
                              attention_mask=mask)
        tokens2 = tokens.at[:, pad_from:].set(7)
        seq2, _ = model.apply({"params": params}, tokens2,
                              attention_mask=mask)
        np.testing.assert_allclose(seq1[:, :pad_from], seq2[:, :pad_from],
                                   rtol=1e-5, atol=1e-6)

    def test_bf16_policy(self):
        cfg, model, batch, params = self._mk(policy=get_policy("O2"))
        mlm, nsp = model.apply({"params": params}, batch["tokens"])
        assert np.all(np.isfinite(np.asarray(mlm, np.float32)))


class TestResNet:
    @pytest.mark.slow
    def test_forward_and_grads(self):
        cfg = ResNetConfig.tiny()
        model = ResNet(cfg)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                        jnp.float32)
        variables = model.init(jax.random.key(0), x)
        logits, mutated = model.apply(
            variables, x, mutable=["batch_stats"])
        assert logits.shape == (2, cfg.num_classes)
        assert "batch_stats" in mutated

        def loss(p):
            out, _ = model.apply(
                {"params": p, "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"])
            return jnp.mean(jnp.square(out))

        g = jax.grad(loss)(variables["params"])
        for leaf in jax.tree.leaves(g):
            assert np.all(np.isfinite(leaf))

    def test_eval_mode_uses_running_stats(self):
        cfg = ResNetConfig.tiny()
        model = ResNet(cfg)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                        jnp.float32)
        variables = model.init(jax.random.key(0), x)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, cfg.num_classes)

    def test_syncbn_dp_matches_full_batch(self, devices):
        """SyncBN over dp=4 shards ≡ single-device full batch (the core
        reference SyncBatchNorm guarantee, here inside a real model)."""
        cfg = ResNetConfig.tiny(bn_axis_name="dp")
        cfg_local = ResNetConfig.tiny()
        model = ResNet(cfg)
        model_local = ResNet(cfg_local)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 16, 16, 3)),
                        jnp.float32)
        variables = model_local.init(jax.random.key(0), x)
        mesh = make_mesh(dp=4, devices=devices[:4])

        def fwd(v, xb):
            out, _ = model.apply(v, xb, mutable=["batch_stats"])
            return out

        sharded = jax.jit(jax.shard_map(
            fwd, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P("dp")))
        got = sharded(variables, x)
        want, _ = model_local.apply(variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestSpatialBottleneck:
    def test_matches_unsplit_bottleneck(self, rng, devices):
        """H-sharded SpatialBottleneck == plain Bottleneck on the full
        activation (the reference's spatial-parallelism guarantee)."""
        from apex1_tpu.models.resnet import Bottleneck, SpatialBottleneck

        cfg = ResNetConfig.tiny()
        x = jnp.asarray(rng.normal(size=(2, 16, 8, 16)), jnp.float32)
        plain = Bottleneck(cfg, features=4)
        variables = plain.init(jax.random.key(0), x)
        mesh = make_mesh(cp=4, dp=1, devices=devices[:4])
        spatial = SpatialBottleneck(cfg, features=4)

        for train in (False, True):
            # train=True also checks BN batch stats span the FULL
            # activation (the spatial axis joins the stats psum)
            want, _ = plain.apply(variables, x, train=train,
                                  mutable=["batch_stats"])

            def fwd(v, xs, train=train):
                out, _ = spatial.apply(v, xs, train=train,
                                       mutable=["batch_stats"])
                return out

            got = jax.jit(jax.shard_map(
                fwd, mesh=mesh,
                in_specs=(P(), P(None, "cp")),
                out_specs=P(None, "cp")))(variables, x)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"train={train}")


class TestParamSpecs:
    """TP PartitionSpec rules per model: sharding the params with
    `param_specs` on a tp mesh must not change the math (GSPMD inserts
    the reference's Column/RowParallel collectives)."""

    def _tp_mesh(self):
        import jax
        from apex1_tpu.core.mesh import make_mesh
        return make_mesh(tp=4, devices=jax.devices()[:4])

    def _check(self, loss_fn, params, specs, mesh, *batch):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        ref = float(jax.jit(loss_fn)(params, *batch))
        sharded = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda v: isinstance(v, P)))
        got = float(jax.jit(loss_fn)(sharded, *batch))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_gpt2_specs(self):
        import jax
        from jax.sharding import PartitionSpec as P
        from apex1_tpu.models import gpt2 as g
        from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)),
                             jnp.int32)
        params = model.init(jax.random.key(0), tokens)["params"]
        specs = g.param_specs(params)
        assert specs["wte"] == P("tp", None)
        assert specs["h0"]["qkv"]["kernel"] == P(None, "tp")
        assert specs["h0"]["proj"]["kernel"] == P("tp", None)
        assert specs["lnf_scale"] == P()
        self._check(gpt2_loss_fn(model), params, specs, self._tp_mesh(),
                    tokens)

    def test_bert_specs(self):
        import jax
        from jax.sharding import PartitionSpec as P
        from apex1_tpu.models import bert as b
        cfg = BertConfig.tiny()
        model = BertPretrain(cfg)
        rng = np.random.default_rng(0)
        batch = {
            "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)),
                                  jnp.int32),
            "mlm_labels": jnp.asarray(
                np.where(rng.random((2, 32)) < 0.15,
                         rng.integers(0, cfg.vocab_size, (2, 32)), -1),
                jnp.int32),
            "nsp_labels": jnp.asarray(rng.integers(0, 2, (2,)), jnp.int32),
        }
        params = model.init(jax.random.key(0), batch["tokens"])["params"]
        specs = b.param_specs(params)
        assert specs["bert"]["word_embeddings"] == P("tp", None)
        assert specs["bert"]["layer0"]["qkv"]["kernel"] == P(None, "tp")
        assert specs["mlm_bias"] == P("tp")
        self._check(bert_pretrain_loss_fn(model), params, specs,
                    self._tp_mesh(), batch)

    def test_t5_specs(self):
        import jax
        from jax.sharding import PartitionSpec as P
        from apex1_tpu.models import t5 as t
        from apex1_tpu.models.t5 import T5, T5Config, t5_loss_fn
        cfg = T5Config.tiny()
        model = T5(cfg)
        rng = np.random.default_rng(0)
        enc = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)),
                          jnp.int32)
        dec = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)),
                          jnp.int32)
        params = model.init(jax.random.key(0), enc, dec)["params"]
        specs = t.param_specs(params)
        assert specs["shared_embedding"] == P("tp", None)
        assert specs["encoder"]["layer0"]["self_attn"]["wq"] == \
            P(None, "tp")
        assert specs["encoder"]["layer0"]["self_attn"]["wo"] == \
            P("tp", None)
        assert specs["encoder"]["rel_pos"]["rel_bias"] == P()
        self._check(t5_loss_fn(model), params, specs, self._tp_mesh(),
                    enc, dec)
