"""GPT-2 with a TIED embedding/LM-head split across pipeline stages —
the reference's embedding-group flow (`parallel_state
:: initialize_model_parallel` builds {first, last}-stage groups; the
schedules all-reduce tied word-embedding grads after each pipeline
step, SURVEY §3.4).

Mesh-native form: ONE shard_mapped train step over a pp mesh —
`schedules.pipeline_tied_apply` routes the tied table (embed on stage
0, LM head on stage P−1, partial-loss convention) and
`schedules.allreduce_embedding_grads` is the embedding-group
all-reduce. Transformer blocks are the pipeline stages.

``python examples/gpt2_pp_tied.py [--pp 4] [--steps 20] [--seq 64]``
(runs on the virtual CPU mesh; pass a real mesh size on hardware)
"""

import argparse
import os
import sys

# direct `python examples/...` puts examples/ (not the repo root) on the
# path; the smoke harness exec()s the source with no __file__ at all
# (no import-time honor_jax_platforms_env here: this example calls
# force_virtual_cpu_devices in main, which must win the first backend
# init — an early default_backend() probe would pin 1 CPU device)
_root = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
         if "__file__" in globals() else os.getcwd())
sys.path.insert(0, _root)

from apex1_tpu.testing import force_virtual_cpu_devices  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--mb", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()
    force_virtual_cpu_devices(max(args.pp, 2))

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as Ps

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.ops import (layer_norm,
                               scaled_upper_triang_masked_softmax,
                               softmax_cross_entropy_loss)
    from apex1_tpu.optim.fused_adam import FusedAdamState, fused_adam
    from apex1_tpu.transformer.pipeline_parallel import schedules

    P_, L, E, H = args.pp, args.layers, args.hidden, args.heads
    V, mb, M, S = args.vocab, args.mb, args.microbatches, args.seq
    assert L % P_ == 0, "--layers must divide by --pp"
    lps = L // P_
    D = E // H
    mesh = make_mesh(pp=P_)
    rng = np.random.default_rng(0)

    def w(*shape, scale=0.02):
        return jnp.asarray(rng.normal(size=shape) * scale, jnp.float32)

    # per-stage transformer-block params, chunk-major (V=1, P, lps, ...)
    chunk = {
        "ln1_g": jnp.ones((1, P_, lps, E)), "ln1_b": jnp.zeros((1, P_, lps, E)),
        "wqkv": w(1, P_, lps, E, 3 * E), "wo": w(1, P_, lps, E, E),
        "ln2_g": jnp.ones((1, P_, lps, E)), "ln2_b": jnp.zeros((1, P_, lps, E)),
        "w1": w(1, P_, lps, E, 4 * E), "w2": w(1, P_, lps, 4 * E, E),
    }
    tied = {"wte": w(V, E), "wpe": w(S, E, scale=0.01)}

    def block(x, p):  # x: (mb, S, E)
        h = layer_norm(x, p["ln1_g"], p["ln1_b"])
        qkv = (h @ p["wqkv"]).reshape(mb, S, 3, H, D)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        a = scaled_upper_triang_masked_softmax(s_, scale=1.0 / np.sqrt(D))
        o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        x = x + o.transpose(0, 2, 1, 3).reshape(mb, S, E) @ p["wo"]
        h = layer_norm(x, p["ln2_g"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]

    def stage_fn(p_stage, x):
        for j in range(lps):
            x = block(x, jax.tree.map(lambda l, j=j: l[j], p_stage))
        return x

    def embed_fn(tied, tokens):  # (mb, S) -> (mb, S, E)
        return tied["wte"][tokens] + tied["wpe"][None]

    def make_head_fn(labels):
        def head_fn(tied, outs):  # (M, mb, S, E) -> (M,) mean CE
            logits = jnp.einsum("mbse,ve->mbsv", outs, tied["wte"])
            ce = softmax_cross_entropy_loss(
                logits[:, :, :-1].reshape(M * mb, S - 1, V),
                labels.reshape(M * mb, S)[:, 1:])
            return jnp.mean(ce.reshape(M, -1), axis=1)
        return head_fn

    tx = fused_adam(1e-3)
    params = {"chunk": chunk, "tied": tied}
    state = {"params": params, "opt": tx.init(params)}
    cspecs = jax.tree.map(lambda _: Ps(None, "pp"), chunk)
    pspecs = {"chunk": cspecs, "tied": {"wte": Ps(), "wpe": Ps()}}
    sspecs = {"params": pspecs,
              "opt": FusedAdamState(step=Ps(), exp_avg=pspecs,
                                    exp_avg_sq=pspecs)}

    def train_step(state, tokens):
        def scalar(params):
            local = jax.tree.map(lambda p: p[:, 0], params["chunk"])
            per_mb = schedules.pipeline_tied_apply(
                stage_fn, local, embed_fn, make_head_fn(tokens),
                params["tied"], tokens, broadcast_outputs=False)
            return jnp.mean(per_mb)  # PARTIAL over pp

        loss_part, grads = jax.value_and_grad(scalar)(state["params"])
        loss = jax.lax.psum(loss_part, "pp")
        # the embedding-group all-reduce: tied grads live on stage 0
        # (embedding use) and stage P-1 (head use); middle stages: zeros
        grads["tied"] = schedules.allreduce_embedding_grads(grads["tied"])
        updates, new_opt = tx.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt": new_opt}, loss

    tokens = jnp.asarray(rng.integers(0, V, (M, mb, S)), jnp.int32)
    # next-token targets come from the SAME tokens argument (shift inside
    # head_fn), so a new batch per step scores against its own labels

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh, in_specs=(sspecs, Ps()),
        out_specs=(sspecs, Ps()), check_vma=False), donate_argnums=0)

    for i in range(args.steps):
        state, loss = step(state, tokens)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}", flush=True)
    print("tied-embedding pipeline OK (embedding-group grads combined "
          f"across {P_} stages)")


if __name__ == "__main__":
    main()
