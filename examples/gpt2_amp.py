"""GPT-2 mixed-precision training — the amp half of reference
``examples/imagenet/main_amp.py`` applied to BASELINE config 1 ("GPT-2
125M, amp O1 + Adam"): opt-level presets, dynamic loss scaling with
skip-on-overflow, fused Adam. Data rides the native runtime: a
memory-mapped `TokenDataset` (step-indexed, resumable) behind a
`PrefetchLoader` (host work + H2D transfer overlapped with device
compute — the reference prefetcher's side-stream overlap). Without
``--data`` a synthetic token file is generated.

``python examples/gpt2_amp.py [--opt-level O1|O1_fp16|O2] [--tiny]
                              [--data tokens.bin]``
"""

import argparse
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu.amp import Amp
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
from apex1_tpu.optim.fused_adam import fused_adam
from apex1_tpu.runtime import PrefetchLoader, TokenDataset, write_token_file
from apex1_tpu.utils.observability import MetricsLogger


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--opt-level", default="O1")
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--data", default=None,
                    help="flat uint16 token file (default: synthetic)")
    args = ap.parse_args()

    policy = get_policy(args.opt_level)
    cfg = (GPT2Config.tiny(policy=policy) if args.tiny
           else GPT2Config(policy=policy))
    if args.seq > cfg.max_seq_len:   # --tiny keeps the default --seq
        args.seq = cfg.max_seq_len
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.seq)), jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]

    amp = Amp(tx=fused_adam(3e-4, weight_decay=0.01),
              opt_level=args.opt_level, max_grad_norm=1.0)
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(gpt2_loss_fn(model)),
                   donate_argnums=0)

    data_path = args.data
    if data_path is None:
        n_tok = max(args.batch * args.seq * 8, 1 << 18)
        data_path = os.path.join(
            tempfile.gettempdir(),
            f"gpt2_amp_synth_{cfg.vocab_size}_{n_tok}_{os.getuid()}.bin")
        if not os.path.exists(data_path):
            # write-then-rename: an interrupted write must never leave a
            # truncated file at the cached name
            tmp = f"{data_path}.tmp.{os.getpid()}"
            write_token_file(tmp, rng.integers(
                0, cfg.vocab_size, n_tok).astype(np.uint16))
            os.replace(tmp, data_path)

    logger = MetricsLogger()
    t0 = time.time()
    with TokenDataset(data_path, seq_len=args.seq,
                      batch_size=args.batch) as ds:
        it = iter(PrefetchLoader(ds.iter_from(0), prefetch=2))
        try:
            for i, batch in zip(range(args.steps), it):
                state, metrics = step(state, jnp.asarray(batch))
                if i % 5 == 0 or i == args.steps - 1:
                    logger.log(i, metrics, tokens=args.batch * args.seq)
        finally:
            # stop the prefetch worker BEFORE the dataset's mmap goes away
            it.close()
    jax.block_until_ready(state.params)
    print(f"done in {time.time() - t0:.1f}s; final loss-scale "
          f"{float(state.loss_scale.scale)}, "
          f"skipped {int(state.loss_scale.overflow_count)} steps")


if __name__ == "__main__":
    main()
