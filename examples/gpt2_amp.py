"""GPT-2 mixed-precision training — the amp half of reference
``examples/imagenet/main_amp.py`` applied to BASELINE config 1 ("GPT-2
125M, amp O1 + Adam"): opt-level presets, dynamic loss scaling with
skip-on-overflow, fused Adam. Data rides the native runtime: a
memory-mapped `TokenDataset` (step-indexed, resumable) behind a
`PrefetchLoader` (host work + H2D transfer overlapped with device
compute — the reference prefetcher's side-stream overlap). Without
``--data`` a synthetic token file is generated.

``python examples/gpt2_amp.py [--opt-level O1|O1_fp16|O2] [--tiny]
                              [--data tokens.bin]``

With ``--ckpt-dir`` the loop runs under the resilient runtime
(`apex1_tpu.resilience`, docs/robustness.md): async integrity-checked
checkpoints every ``--ckpt-every`` steps, ``--resume auto`` continuing
EXACTLY from the newest valid checkpoint (step-indexed `TokenDataset`
⇒ the data position is just the step), a divergence sentinel
(skip → rollback → abort), and a SIGTERM/SIGINT preemption hook that
banks a final synchronous checkpoint and exits `EXIT_RESUMABLE` (75)
so `tools/tpu_watch.sh` re-queues instead of recording a failure.
``APEX1_CHAOS_SIGTERM_STEP=<n>`` self-injects the preemption at step n
(the chaos harness's kill-and-resume drill).

``--obs-dir <dir>`` (or ``APEX1_OBS_DIR``) banks the run through the
telemetry spine (`apex1_tpu.obs`, docs/observability.md): every
`MetricsLogger` line, sentinel diagnostic, and checkpoint event lands
in one run-scoped JSONL file, joinable with bench/tuning/serving runs.
"""

import argparse
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu.amp import Amp
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
from apex1_tpu.optim.fused_adam import fused_adam
from apex1_tpu.runtime import PrefetchLoader, TokenDataset, write_token_file
from apex1_tpu.utils.observability import MetricsLogger


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--opt-level", default="O1")
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--data", default=None,
                    help="flat uint16 token file (default: synthetic)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="enable the resilient runtime: async "
                    "checkpoints + sentinel + preemption hook")
    ap.add_argument("--ckpt-every", type=int, default=5)
    ap.add_argument("--resume", default="auto", choices=("auto", "never"),
                    help="auto: continue from the newest VALID "
                    "checkpoint under --ckpt-dir")
    ap.add_argument("--obs-dir", default=None,
                    help="bank run telemetry (metrics, sentinel "
                    "diagnostics) as JSONL through apex1_tpu.obs; "
                    "equivalent to setting APEX1_OBS_DIR")
    args = ap.parse_args()

    if args.obs_dir:
        # the spine's default run resolves this lazily at first emit,
        # so setting it before the loop wires every MetricsLogger line
        os.environ["APEX1_OBS_DIR"] = args.obs_dir

    policy = get_policy(args.opt_level)
    cfg = (GPT2Config.tiny(policy=policy) if args.tiny
           else GPT2Config(policy=policy))
    if args.seq > cfg.max_seq_len:   # --tiny keeps the default --seq
        args.seq = cfg.max_seq_len
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.seq)), jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]

    amp = Amp(tx=fused_adam(3e-4, weight_decay=0.01),
              opt_level=args.opt_level, max_grad_norm=1.0)
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(gpt2_loss_fn(model)),
                   donate_argnums=0)

    data_path = args.data
    if data_path is None:
        n_tok = max(args.batch * args.seq * 8, 1 << 18)
        data_path = os.path.join(
            tempfile.gettempdir(),
            f"gpt2_amp_synth_{cfg.vocab_size}_{n_tok}_{os.getuid()}.bin")
        if not os.path.exists(data_path):
            # write-then-rename: an interrupted write must never leave a
            # truncated file at the cached name
            tmp = f"{data_path}.tmp.{os.getpid()}"
            write_token_file(tmp, rng.integers(
                0, cfg.vocab_size, n_tok).astype(np.uint16))
            os.replace(tmp, data_path)

    logger = MetricsLogger()
    t0 = time.time()
    if args.ckpt_dir:
        state = _resilient_loop(args, amp, model, state, data_path, logger)
    else:
        with TokenDataset(data_path, seq_len=args.seq,
                          batch_size=args.batch) as ds:
            it = iter(PrefetchLoader(ds.iter_from(0), prefetch=2))
            try:
                for i, batch in zip(range(args.steps), it):
                    state, metrics = step(state, jnp.asarray(batch))
                    if i % 5 == 0 or i == args.steps - 1:
                        logger.log(i, metrics,
                                   tokens=args.batch * args.seq)
            finally:
                # stop the prefetch worker BEFORE the mmap goes away
                it.close()
    jax.block_until_ready(state.params)
    print(f"done in {time.time() - t0:.1f}s; final loss-scale "
          f"{float(state.loss_scale.scale)}, "
          f"skipped {int(state.loss_scale.overflow_count)} steps")


def _resilient_loop(args, amp, model, state, data_path, logger):
    """The --ckpt-dir path: the same train step under the resilient
    runtime. `TokenDataset.batch_at(step)` is a pure function of the
    step, so the data-iterator position in the checkpoint meta is just
    an int and resume/rollback are exact."""
    from apex1_tpu.resilience import (PreemptionHandler,
                                      ResilientCheckpointer, Sentinel,
                                      sentinel_init)
    from apex1_tpu.testing.chaos import sigterm_self_at
    from apex1_tpu.utils.debug import program_fingerprint

    sample = jnp.zeros((args.batch, args.seq), jnp.int32)
    plain_step = amp.make_train_step(gpt2_loss_fn(model))
    sent = Sentinel(None, check_every=max(1, args.ckpt_every),
                    rollback_after=2)
    guarded = jax.jit(sent.guard(plain_step), donate_argnums=0)
    fp = program_fingerprint(sent.guard(plain_step),
                             (state, sentinel_init()), sample)
    ck = ResilientCheckpointer(args.ckpt_dir, keep=3, fingerprint=fp)
    sent.checkpointer = ck
    chaos_at = os.environ.get("APEX1_CHAOS_SIGTERM_STEP")
    chaos_at = int(chaos_at) if chaos_at else None

    start = 0
    carry = (state, sentinel_init())
    if args.resume == "auto" and ck.latest_valid() is not None:
        restored, man = ck.restore(template=carry[0])
        start = int(man.meta.get("data_step", man.step))
        carry = (restored, sentinel_init())
        print(f"resumed from {man.step} (data step {start})", flush=True)

    with TokenDataset(data_path, seq_len=args.seq,
                      batch_size=args.batch) as ds, \
            PreemptionHandler() as pre, ck:
        i = start
        while i < args.steps:
            step_idx = i
            carry, metrics = guarded(carry, jnp.asarray(ds.batch_at(i)))
            i += 1
            action = sent.poll(carry[1])
            if action == "rollback":
                restored, man, s0 = sent.rollback(template=carry[0])
                i = int(man.meta.get("data_step", man.step))
                carry = (restored, s0)
                # This loss is deterministic, so the retry replays the
                # same trajectory on purpose: a TRANSIENT fault (SDC
                # bit flip) won't recur and training continues; a
                # LOGICAL NaN recurs and the ladder escalates to abort
                # with the diagnostics banked. A stochastic run would
                # additionally re-fold its dropout stream here —
                # resilience.refold_key(key, sent.rollbacks_done) — so
                # the retry draws different noise (docs/robustness.md).
                print(f"sentinel rollback to data step {i}", flush=True)
                continue
            if i % args.ckpt_every == 0 or i == args.steps:
                ck.save(int(carry[0].step), carry[0],
                        meta={"data_step": i})
            # same cadence as the plain loop: steps 0, 5, 10, ..., last
            if step_idx % 5 == 0 or step_idx == args.steps - 1:
                logger.log(step_idx, metrics,
                           tokens=args.batch * args.seq)
            sigterm_self_at(i, chaos_at)
            if pre.triggered:
                ck.wait()   # let the in-flight async save commit first
                ck.save_sync(int(carry[0].step), carry[0],
                             meta={"data_step": i, "preempted": True})
                pre.exit_resumable(f"preempted at data step {i}")
        ck.wait()
    return carry[0]


if __name__ == "__main__":
    main()
