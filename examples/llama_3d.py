"""Llama under full 3D parallelism — dp × pp × tp (+ SP), the BASELINE
config-4 composition (`apex1_tpu.models.llama_3d`) as a runnable loop.

One `shard_map` train step: Megatron TP+SP blocks inside a scan+ppermute
pipeline (optionally interleaved, ``--chunks 2``), vocab-parallel
embedding + fused LM-head CE with embedding-group grad combination,
fused Adam on fp32 masters. Defaults run a tiny model on the virtual
CPU mesh; the same code compiles for a v5p-32 class topology at 8B
(`tools/aot_check.py --flagship`).

Two ways to pick the parallel layout:

- by hand: ``--dp 2 --pp 2 --tp 2`` etc. — every axis flag is
  validated against `apex1_tpu.planner.check_layout` BEFORE anything
  compiles, and an illegal combination exits loudly NAMING the broken
  rule (tp not dividing heads, pp exceeding layers, ...) instead of
  failing deep inside `shard_map`;
- by search: ``--plan auto`` hands the same model to the
  auto-parallel planner (`apex1_tpu.planner`), which enumerates the
  legal layouts for ``--devices`` chips, prices them with the
  calibrated cost model, and drives this loop from the winning plan —
  whose partition rules are verified against the model's own specs
  before training starts. ``--plan <path>`` replays a banked plan
  document instead of searching.

With ``--ckpt-dir`` the loop runs under the resilient runtime
(`apex1_tpu.resilience`, docs/robustness.md): every checkpoint banks
its producing ``apex1-plan-v1`` spec (hand layouts are turned into a
stated plan via `planner.plan_for_layout`, so EVERY checkpoint is
self-describing and reshardable), ``--resume auto`` continues from
the newest valid checkpoint (per-step-seeded batches ⇒ the data
position is one int in the manifest meta), a SIGTERM preemption hook
banks a final sync checkpoint and exits 75
(``APEX1_CHAOS_SIGTERM_STEP=<n>`` self-injects the kill), and
``--elastic`` survives a CHANGED fleet: on relaunch with a different
``--devices``, `resilience.elastic_resume` re-plans the surviving
chip count with the planner, reshards the checkpoint
(manifest-verified), and resumes — the checkpoint's banked plan, not
the axis flags, is the authority for the model.

``python examples/llama_3d.py [--dp 2 --pp 2 --tp 2] [--chunks 2]``
``python examples/llama_3d.py --plan auto [--devices 8]``
``python examples/llama_3d.py --elastic --ckpt-dir /tmp/ck --devices 4``
"""

import argparse
import json
import os
import sys
import time

# (no import-time honor_jax_platforms_env here: this example calls
# force_virtual_cpu_devices in main, which must win the first backend
# init — an early default_backend() probe would pin 1 CPU device)
_root = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
         if "__file__" in globals() else os.getcwd())
sys.path.insert(0, _root)

from apex1_tpu import planner  # noqa: E402
from apex1_tpu.testing import force_virtual_cpu_devices  # noqa: E402


def _model_shape(args) -> planner.ModelShape:
    """The planner's view of the tiny example model — dims mirror the
    LlamaConfig.tiny(...) construction below (heads/kv fixed 4/2)."""
    return planner.ModelShape(
        name="llama3d-example", num_layers=args.layers,
        hidden_size=args.hidden, ffn_size=2 * args.hidden,
        num_heads=4, num_kv_heads=2, head_dim=args.hidden // 4,
        vocab_size=args.vocab, seq_len=args.seq,
        global_batch=args.microbatches * args.dp * args.ep,
        num_experts=4 if args.moe else 0, moe_top_k=2)


def _validate_hand_layout(args) -> None:
    """The satellite fix: the hand axis flags used to be checked only
    as a device product; every other rule surfaced as a shard_map or
    Llama3DConfig traceback. Now the planner's legality predicate
    rejects them up front, one named rule per line, exit 2."""
    layout = planner.Layout(
        dp=args.dp, pp=args.pp, cp=args.cp, ep=args.ep, tp=args.tp,
        num_microbatches=args.microbatches, microbatch_size=1,
        num_chunks=args.chunks, schedule=args.schedule)
    violations = planner.check_layout(_model_shape(args), layout)
    if violations:
        print("ILLEGAL LAYOUT — rejected by apex1_tpu.planner."
              "check_layout before compiling anything:",
              file=sys.stderr, flush=True)
        for v in violations:
            print(f"  [{v.rule}] {v.message}", file=sys.stderr,
                  flush=True)
        print("(see docs/planner.md for the rule catalogue; "
              "`--plan auto` searches only legal layouts)",
              file=sys.stderr, flush=True)
        sys.exit(2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1,
                    help="context parallel (ring attention seq shards)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert parallel (implies --moe)")
    ap.add_argument("--moe", action="store_true",
                    help="every FFN expert-routed (4 experts, top-2)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--schedule", default="scan",
                    choices=("scan", "1f1b"),
                    help="pipeline schedule: scan (remat) or the true "
                         "staggered-fwd/bwd 1F1B (interleaved with "
                         "--chunks > 1)")
    ap.add_argument("--plan", default=None, metavar="auto|PATH",
                    help="'auto': search dp x pp x cp x ep x tp with "
                         "the calibrated planner instead of the axis "
                         "flags; PATH: replay a banked plan.json")
    ap.add_argument("--devices", type=int, default=None,
                    help="chip count for --plan auto / --elastic "
                         "(default: the product of the axis flags)")
    ap.add_argument("--seed", type=int, default=0,
                    help="data seed: batch i is a pure function of "
                         "(seed, i), so resume is exact")
    ap.add_argument("--ckpt-dir", default=None,
                    help="enable the resilient runtime: plan-banking "
                         "checkpoints + preemption hook + resume")
    ap.add_argument("--ckpt-every", type=int, default=2)
    ap.add_argument("--resume", default="auto", choices=("auto",
                                                         "never"))
    ap.add_argument("--elastic", action="store_true",
                    help="on relaunch, survive a changed --devices: "
                         "planner re-plan + manifest-verified "
                         "checkpoint reshard (needs --ckpt-dir)")
    args = ap.parse_args()
    if args.ep > 1:
        args.moe = True
    if args.elastic and not args.ckpt_dir:
        print("--elastic requires --ckpt-dir", file=sys.stderr,
              flush=True)
        sys.exit(2)

    elastic_src = None
    if args.elastic:
        from apex1_tpu.resilience import find_restorable

        elastic_src = find_restorable(args.ckpt_dir)

    plan = None
    if elastic_src is not None:
        # elastic relaunch: the checkpoint's banked plan is the
        # authority for the model AND the layout; the re-plan happens
        # after the backend comes up (the reshard needs arrays)
        n = args.devices or (args.dp * args.pp * args.tp * args.ep
                             * args.cp)
    elif args.plan:
        n = args.devices or (args.dp * args.pp * args.tp * args.ep
                             * args.cp)
        if args.plan == "auto":
            # zero stays off: the example's step shards optimizer
            # state like params (GSPMD); the dp-axis ZeRO split is
            # priced for 8B-scale plans, not exercised by this loop
            plan = planner.make_plan(_model_shape(args), n,
                                     allow_zero=False)
        else:
            plan = planner.load_plan(args.plan)
            n = plan["n_devices"]
            # a replayed plan must price THIS model: the schedule and
            # partition rules are only valid for the dims it priced
            mismatch = planner.check_plan_model(plan,
                                                _model_shape(args))
            if mismatch:
                raise SystemExit(
                    "plan/model mismatch — this plan was searched for "
                    "a different model than the flags describe:\n  "
                    + "\n  ".join(mismatch))
        m, sch = plan["mesh"], plan["schedule"]
        args.dp, args.pp, args.tp = m["dp"], m["pp"], m["tp"]
        args.cp, args.ep = m["cp"], m["ep"]
        args.microbatches = sch["num_microbatches"]
        args.chunks = sch["num_chunks"]
        args.schedule = sch["kind"]
        args.moe = args.moe or bool(plan["model"].get("num_experts"))
        pr = plan["predicted"]
        print(f"plan: mesh dp={m['dp']} pp={m['pp']} cp={m['cp']} "
              f"ep={m['ep']} tp={m['tp']} M={sch['num_microbatches']} "
              f"sp={plan['kernel_flags']['sp_boundary']} — "
              f"{pr['calibrated_step_ms']:.3f} ms/step calibrated "
              f"[{pr['calibration']['source']}], "
              f"{plan['search']['n_enumerated']} layouts searched, "
              f"{plan['search']['n_hbm_rejected']} over HBM",
              flush=True)
        if plan["zero"]["enabled"]:
            print("note: plan prices ZeRO optimizer sharding; this "
                  "example runs the GSPMD param-sharded default "
                  "(consumer: parallel.distributed_optimizer)",
                  flush=True)
    else:
        _validate_hand_layout(args)
        n = args.dp * args.pp * args.tp * args.ep * args.cp
    force_virtual_cpu_devices(max(n, 2))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import (Llama3DConfig,
                                           chunk_param_specs,
                                           make_train_step,
                                           shared_param_specs,
                                           state_template)

    def mcfg_from_plan(p):
        """LlamaConfig for a plan's banked model dims — the elastic
        path's model authority (mirrors the flag-driven construction
        below; the plan carries dims, not the precision policy)."""
        pm = p["model"]
        kw = (dict(moe_every=1, num_experts=pm["num_experts"],
                   moe_top_k=pm["moe_top_k"], moe_capacity_factor=2.0)
              if pm.get("num_experts") else {})
        return LlamaConfig.tiny(
            num_layers=pm["num_layers"], max_seq_len=pm["seq_len"],
            vocab_size=pm["vocab_size"], num_heads=pm["num_heads"],
            num_kv_heads=pm["num_kv_heads"],
            hidden_size=pm["hidden_size"], ffn_size=pm["ffn_size"],
            policy=get_policy("O2"), **kw)

    decision = None
    if elastic_src is not None:
        from apex1_tpu.resilience.elastic import elastic_resume

        def make_template(p):
            return state_template(planner.llama3d_config_from_plan(
                p, mcfg_from_plan(p), learning_rate=3e-3,
                ignore_zero=True))

        from apex1_tpu.resilience import LayoutMismatch

        try:
            decision = elastic_resume(args.ckpt_dir, n_devices=n,
                                      make_template=make_template,
                                      planner_kw={"allow_zero": False})
        except (LayoutMismatch, planner.PlanError) as e:
            # e.g. a pre-elastic checkpoint without plan meta, or no
            # legal layout for the surviving chip count: the typed
            # message says what to do — no traceback needed
            print(str(e), file=sys.stderr, flush=True)
            sys.exit(2)
        plan = decision.plan
        m, sch = plan["mesh"], plan["schedule"]
        args.dp, args.pp, args.tp = m["dp"], m["pp"], m["tp"]
        args.cp, args.ep = m["cp"], m["ep"]
        args.microbatches = sch["num_microbatches"]
        args.chunks, args.schedule = sch["num_chunks"], sch["kind"]
        pm = plan["model"]
        args.layers, args.hidden = pm["num_layers"], pm["hidden_size"]
        args.seq, args.vocab = pm["seq_len"], pm["vocab_size"]
        args.moe = bool(pm.get("num_experts"))
        if decision.resharded:
            rep = decision.report
            print(f"elastic: fleet {decision.old_plan['n_devices']} "
                  f"-> {n} devices; re-planned and resharded "
                  f"({rep['n_restacked']} restacked / "
                  f"{rep['n_repacked']} repacked / {rep['n_copied']} "
                  f"copied leaves, digest-verified) -> "
                  f"{decision.path}", flush=True)
        else:
            print(f"elastic: fleet unchanged ({n} devices); plain "
                  f"resume from {decision.path}", flush=True)

    moe_kw = (dict(moe_every=1, num_experts=4, moe_top_k=2,
                   moe_capacity_factor=2.0) if args.moe else {})
    mcfg = (mcfg_from_plan(plan) if decision is not None
            else LlamaConfig.tiny(
                num_layers=args.layers, max_seq_len=args.seq,
                vocab_size=args.vocab, num_heads=4, num_kv_heads=2,
                hidden_size=args.hidden, ffn_size=2 * args.hidden,
                policy=get_policy("O2"), **moe_kw))
    if plan is not None:
        # ignore_zero: the note above told the user this loop runs the
        # unsharded optimizer; at tiny example scale that always fits
        cfg = planner.llama3d_config_from_plan(plan, mcfg,
                                               learning_rate=3e-3,
                                               ignore_zero=True)
    else:
        cfg = Llama3DConfig(model=mcfg, dp=args.dp, pp=args.pp,
                            tp=args.tp, cp=args.cp, ep=args.ep,
                            moe=args.moe, num_chunks=args.chunks,
                            num_microbatches=args.microbatches,
                            microbatch_size=1, learning_rate=3e-3,
                            schedule=args.schedule)
    step, state, _ = make_train_step(cfg)
    if plan is not None:
        # the emitted regex rules must reproduce the model's own
        # hand-written specs leaf-for-leaf — a plan that drifts from
        # the model is caught HERE, not as a wrong-layout slowdown
        got = planner.plan_param_specs(plan, state["params"])
        cspecs = chunk_param_specs(cfg)
        want = {"chunk": {k: cspecs[k]
                          for k in state["params"]["chunk"]},
                "shared": shared_param_specs()}
        if got != want:
            raise SystemExit(
                f"plan partition rules drifted from "
                f"models.llama_3d specs:\n got {got}\nwant {want}")
        print("plan verified: partition rules reproduce "
              "models.llama_3d specs", flush=True)
    mb_cols = cfg.microbatch_size * cfg.dp * cfg.ep
    global_batch = cfg.num_microbatches * mb_cols

    def batch_at(i):
        # batch i is a pure function of (seed, i), drawn in a
        # CANONICAL (global_batch, seq) layout and regrouped as
        # sequence g = m*B + b -> tokens[m, :, b]. An elastic re-plan
        # that changes the (M, B) factorization therefore still
        # trains the SAME sequences at step i — only the microbatch
        # grouping changes — and the checkpoint's data position stays
        # one int. (A layout-shaped draw would regroup the flat RNG
        # stream into different sequences.)
        r = np.random.default_rng([args.seed, i])
        canon = r.integers(0, args.vocab, (global_batch, args.seq))
        toks = canon.reshape(cfg.num_microbatches, mb_cols,
                             args.seq).transpose(0, 2, 1)
        tokens = jnp.asarray(toks, jnp.int32)
        return tokens, jnp.roll(tokens, -1, axis=1)

    ck = None
    pre = None
    start = 0
    if args.ckpt_dir:
        from apex1_tpu.resilience import (LayoutMismatch,
                                          PreemptionHandler,
                                          ResilientCheckpointer)
        from apex1_tpu.testing.chaos import sigterm_self_at

        if plan is not None:
            bank_plan = plan
            if plan.get("zero", {}).get("enabled"):
                # the banked spec must describe the STATE AS SAVED:
                # this loop runs the UNSHARDED optimizer
                # (ignore_zero=True above), so banking the plan's
                # zero flag verbatim would make a later elastic
                # re-plan require a ZeRO layout the checkpoint does
                # not have
                bank_plan = json.loads(json.dumps(plan))
                bank_plan["zero"]["enabled"] = False
                bank_plan["zero"]["note"] = (
                    "disabled at banking: the llama_3d loop ran the "
                    "unsharded optimizer (ignore_zero=True)")
        else:
            # hand layout: bank the STATED plan so every checkpoint
            # is self-describing and reshardable (the elastic
            # relaunch reads it, never the axis flags)
            bank_plan = planner.plan_for_layout(
                _model_shape(args),
                planner.Layout(dp=args.dp, pp=args.pp, cp=args.cp,
                               ep=args.ep, tp=args.tp,
                               num_microbatches=args.microbatches,
                               num_chunks=args.chunks,
                               schedule=args.schedule))
        ck = ResilientCheckpointer(args.ckpt_dir, keep=3,
                                   plan=bank_plan)
        pre = PreemptionHandler()
        chaos_at = os.environ.get("APEX1_CHAOS_SIGTERM_STEP")
        chaos_at = int(chaos_at) if chaos_at else None
        if decision is not None:
            state, man = ck.restore(template=state,
                                    path=decision.path)
            start = int(man.meta.get("data_step", 0))
            print(f"elastic resume at data step {start} "
                  f"(checkpoint step {man.step}, every leaf "
                  f"digest-verified)", flush=True)
        elif args.resume == "auto" and ck.latest_valid() is not None:
            try:
                state, man = ck.restore(template=state)
            except LayoutMismatch as e:
                print(f"{e}\n(hint: relaunch with --elastic to "
                      f"re-plan and reshard for the new layout)",
                      file=sys.stderr, flush=True)
                sys.exit(2)
            start = int(man.meta.get("data_step", man.step))
            print(f"resumed from step {man.step} "
                  f"(data step {start})", flush=True)

    print(f"mesh dp={cfg.dp} pp={cfg.pp} tp={cfg.tp} ep={cfg.ep} "
          f"cp={cfg.cp} "
          f"chunks={cfg.num_chunks} moe={cfg.moe} ({n} devices), "
          f"{args.layers}L x {args.hidden}h", flush=True)
    t0 = time.time()
    if pre is not None:
        pre.install()
    try:
        for i in range(start, args.steps):
            tokens, labels = batch_at(i)
            state, loss = step(state, tokens, labels)
            if i % 5 == 0 or i == args.steps - 1:
                print(f"step {i:3d}  loss {float(loss):.4f}",
                      flush=True)
            if ck is not None:
                if (i + 1) % args.ckpt_every == 0 or i + 1 == args.steps:
                    ck.save(int(state["step"]), state,
                            meta={"data_step": i + 1})
                sigterm_self_at(i + 1, chaos_at)
                if pre.triggered:
                    ck.wait()   # let the in-flight async save commit
                    ck.save_sync(int(state["step"]), state,
                                 meta={"data_step": i + 1,
                                       "preempted": True})
                    pre.exit_resumable(
                        f"preempted at data step {i + 1}")
        if ck is not None:
            ck.wait()
            ck.close()
    finally:
        if pre is not None:
            pre.uninstall()
    jax.block_until_ready(state)
    print(f"done in {time.time() - t0:.1f}s "
          f"(step counter = {int(state['step'])})")


if __name__ == "__main__":
    main()
