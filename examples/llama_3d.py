"""Llama under full 3D parallelism — dp × pp × tp (+ SP), the BASELINE
config-4 composition (`apex1_tpu.models.llama_3d`) as a runnable loop.

One `shard_map` train step: Megatron TP+SP blocks inside a scan+ppermute
pipeline (optionally interleaved, ``--chunks 2``), vocab-parallel
embedding + fused LM-head CE with embedding-group grad combination,
fused Adam on fp32 masters. Defaults run a tiny model on the virtual
CPU mesh; the same code compiles for a v5p-32 class topology at 8B
(`tools/aot_check.py --flagship`).

``python examples/llama_3d.py [--dp 2 --pp 2 --tp 2] [--chunks 2]``
"""

import argparse
import os
import sys
import time

# (no import-time honor_jax_platforms_env here: this example calls
# force_virtual_cpu_devices in main, which must win the first backend
# init — an early default_backend() probe would pin 1 CPU device)
_root = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
         if "__file__" in globals() else os.getcwd())
sys.path.insert(0, _root)

from apex1_tpu.testing import force_virtual_cpu_devices  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1,
                    help="context parallel (ring attention seq shards)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert parallel (implies --moe)")
    ap.add_argument("--moe", action="store_true",
                    help="every FFN expert-routed (4 experts, top-2)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--schedule", default="scan",
                    choices=("scan", "1f1b"),
                    help="pipeline schedule: scan (remat) or the true "
                         "staggered-fwd/bwd 1F1B (interleaved with "
                         "--chunks > 1)")
    args = ap.parse_args()
    if args.ep > 1:
        args.moe = True
    n = args.dp * args.pp * args.tp * args.ep * args.cp
    force_virtual_cpu_devices(max(n, 2))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import Llama3DConfig, make_train_step

    moe_kw = (dict(moe_every=1, num_experts=4, moe_top_k=2,
                   moe_capacity_factor=2.0) if args.moe else {})
    mcfg = LlamaConfig.tiny(
        num_layers=args.layers, max_seq_len=args.seq,
        vocab_size=args.vocab, num_heads=4, num_kv_heads=2,
        hidden_size=args.hidden, ffn_size=2 * args.hidden,
        policy=get_policy("O2"), **moe_kw)
    cfg = Llama3DConfig(model=mcfg, dp=args.dp, pp=args.pp, tp=args.tp,
                        cp=args.cp, ep=args.ep, moe=args.moe,
                        num_chunks=args.chunks,
                        num_microbatches=args.microbatches,
                        microbatch_size=1, learning_rate=3e-3,
                        schedule=args.schedule)
    step, state, _ = make_train_step(cfg)
    rng = np.random.default_rng(0)
    shape = (args.microbatches, args.seq, args.dp * args.ep)
    print(f"mesh dp={args.dp} pp={args.pp} tp={args.tp} ep={args.ep} "
          f"cp={args.cp} "
          f"chunks={args.chunks} moe={args.moe} ({n} devices), "
          f"{args.layers}L x {args.hidden}h", flush=True)
    t0 = time.time()
    for i in range(args.steps):
        tokens = jnp.asarray(rng.integers(0, args.vocab, shape), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        state, loss = step(state, tokens, labels)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}", flush=True)
    jax.block_until_ready(state)
    print(f"done in {time.time() - t0:.1f}s "
          f"(step counter = {int(state['step'])})")


if __name__ == "__main__":
    main()
