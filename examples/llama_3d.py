"""Llama under full 3D parallelism — dp × pp × tp (+ SP), the BASELINE
config-4 composition (`apex1_tpu.models.llama_3d`) as a runnable loop.

One `shard_map` train step: Megatron TP+SP blocks inside a scan+ppermute
pipeline (optionally interleaved, ``--chunks 2``), vocab-parallel
embedding + fused LM-head CE with embedding-group grad combination,
fused Adam on fp32 masters. Defaults run a tiny model on the virtual
CPU mesh; the same code compiles for a v5p-32 class topology at 8B
(`tools/aot_check.py --flagship`).

Two ways to pick the parallel layout:

- by hand: ``--dp 2 --pp 2 --tp 2`` etc. — every axis flag is
  validated against `apex1_tpu.planner.check_layout` BEFORE anything
  compiles, and an illegal combination exits loudly NAMING the broken
  rule (tp not dividing heads, pp exceeding layers, ...) instead of
  failing deep inside `shard_map`;
- by search: ``--plan auto`` hands the same model to the
  auto-parallel planner (`apex1_tpu.planner`), which enumerates the
  legal layouts for ``--devices`` chips, prices them with the
  calibrated cost model, and drives this loop from the winning plan —
  whose partition rules are verified against the model's own specs
  before training starts. ``--plan <path>`` replays a banked plan
  document instead of searching.

``python examples/llama_3d.py [--dp 2 --pp 2 --tp 2] [--chunks 2]``
``python examples/llama_3d.py --plan auto [--devices 8]``
"""

import argparse
import os
import sys
import time

# (no import-time honor_jax_platforms_env here: this example calls
# force_virtual_cpu_devices in main, which must win the first backend
# init — an early default_backend() probe would pin 1 CPU device)
_root = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
         if "__file__" in globals() else os.getcwd())
sys.path.insert(0, _root)

from apex1_tpu import planner  # noqa: E402
from apex1_tpu.testing import force_virtual_cpu_devices  # noqa: E402


def _model_shape(args) -> planner.ModelShape:
    """The planner's view of the tiny example model — dims mirror the
    LlamaConfig.tiny(...) construction below (heads/kv fixed 4/2)."""
    return planner.ModelShape(
        name="llama3d-example", num_layers=args.layers,
        hidden_size=args.hidden, ffn_size=2 * args.hidden,
        num_heads=4, num_kv_heads=2, head_dim=args.hidden // 4,
        vocab_size=args.vocab, seq_len=args.seq,
        global_batch=args.microbatches * args.dp * args.ep,
        num_experts=4 if args.moe else 0, moe_top_k=2)


def _validate_hand_layout(args) -> None:
    """The satellite fix: the hand axis flags used to be checked only
    as a device product; every other rule surfaced as a shard_map or
    Llama3DConfig traceback. Now the planner's legality predicate
    rejects them up front, one named rule per line, exit 2."""
    layout = planner.Layout(
        dp=args.dp, pp=args.pp, cp=args.cp, ep=args.ep, tp=args.tp,
        num_microbatches=args.microbatches, microbatch_size=1,
        num_chunks=args.chunks, schedule=args.schedule)
    violations = planner.check_layout(_model_shape(args), layout)
    if violations:
        print("ILLEGAL LAYOUT — rejected by apex1_tpu.planner."
              "check_layout before compiling anything:",
              file=sys.stderr, flush=True)
        for v in violations:
            print(f"  [{v.rule}] {v.message}", file=sys.stderr,
                  flush=True)
        print("(see docs/planner.md for the rule catalogue; "
              "`--plan auto` searches only legal layouts)",
              file=sys.stderr, flush=True)
        sys.exit(2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1,
                    help="context parallel (ring attention seq shards)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert parallel (implies --moe)")
    ap.add_argument("--moe", action="store_true",
                    help="every FFN expert-routed (4 experts, top-2)")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--schedule", default="scan",
                    choices=("scan", "1f1b"),
                    help="pipeline schedule: scan (remat) or the true "
                         "staggered-fwd/bwd 1F1B (interleaved with "
                         "--chunks > 1)")
    ap.add_argument("--plan", default=None, metavar="auto|PATH",
                    help="'auto': search dp x pp x cp x ep x tp with "
                         "the calibrated planner instead of the axis "
                         "flags; PATH: replay a banked plan.json")
    ap.add_argument("--devices", type=int, default=None,
                    help="chip count for --plan auto (default: the "
                         "product of the axis flags)")
    args = ap.parse_args()
    if args.ep > 1:
        args.moe = True

    plan = None
    if args.plan:
        n = args.devices or (args.dp * args.pp * args.tp * args.ep
                             * args.cp)
        if args.plan == "auto":
            # zero stays off: the example's step shards optimizer
            # state like params (GSPMD); the dp-axis ZeRO split is
            # priced for 8B-scale plans, not exercised by this loop
            plan = planner.make_plan(_model_shape(args), n,
                                     allow_zero=False)
        else:
            plan = planner.load_plan(args.plan)
            n = plan["n_devices"]
            # a replayed plan must price THIS model: the schedule and
            # partition rules are only valid for the dims it priced
            mismatch = planner.check_plan_model(plan,
                                                _model_shape(args))
            if mismatch:
                raise SystemExit(
                    "plan/model mismatch — this plan was searched for "
                    "a different model than the flags describe:\n  "
                    + "\n  ".join(mismatch))
        m, sch = plan["mesh"], plan["schedule"]
        args.dp, args.pp, args.tp = m["dp"], m["pp"], m["tp"]
        args.cp, args.ep = m["cp"], m["ep"]
        args.microbatches = sch["num_microbatches"]
        args.chunks = sch["num_chunks"]
        args.schedule = sch["kind"]
        args.moe = args.moe or bool(plan["model"].get("num_experts"))
        pr = plan["predicted"]
        print(f"plan: mesh dp={m['dp']} pp={m['pp']} cp={m['cp']} "
              f"ep={m['ep']} tp={m['tp']} M={sch['num_microbatches']} "
              f"sp={plan['kernel_flags']['sp_boundary']} — "
              f"{pr['calibrated_step_ms']:.3f} ms/step calibrated "
              f"[{pr['calibration']['source']}], "
              f"{plan['search']['n_enumerated']} layouts searched, "
              f"{plan['search']['n_hbm_rejected']} over HBM",
              flush=True)
        if plan["zero"]["enabled"]:
            print("note: plan prices ZeRO optimizer sharding; this "
                  "example runs the GSPMD param-sharded default "
                  "(consumer: parallel.distributed_optimizer)",
                  flush=True)
    else:
        _validate_hand_layout(args)
        n = args.dp * args.pp * args.tp * args.ep * args.cp
    force_virtual_cpu_devices(max(n, 2))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import (Llama3DConfig,
                                           chunk_param_specs,
                                           make_train_step,
                                           shared_param_specs)

    moe_kw = (dict(moe_every=1, num_experts=4, moe_top_k=2,
                   moe_capacity_factor=2.0) if args.moe else {})
    mcfg = LlamaConfig.tiny(
        num_layers=args.layers, max_seq_len=args.seq,
        vocab_size=args.vocab, num_heads=4, num_kv_heads=2,
        hidden_size=args.hidden, ffn_size=2 * args.hidden,
        policy=get_policy("O2"), **moe_kw)
    if plan is not None:
        # ignore_zero: the note above told the user this loop runs the
        # unsharded optimizer; at tiny example scale that always fits
        cfg = planner.llama3d_config_from_plan(plan, mcfg,
                                               learning_rate=3e-3,
                                               ignore_zero=True)
    else:
        cfg = Llama3DConfig(model=mcfg, dp=args.dp, pp=args.pp,
                            tp=args.tp, cp=args.cp, ep=args.ep,
                            moe=args.moe, num_chunks=args.chunks,
                            num_microbatches=args.microbatches,
                            microbatch_size=1, learning_rate=3e-3,
                            schedule=args.schedule)
    step, state, _ = make_train_step(cfg)
    if plan is not None:
        # the emitted regex rules must reproduce the model's own
        # hand-written specs leaf-for-leaf — a plan that drifts from
        # the model is caught HERE, not as a wrong-layout slowdown
        got = planner.plan_param_specs(plan, state["params"])
        cspecs = chunk_param_specs(cfg)
        want = {"chunk": {k: cspecs[k]
                          for k in state["params"]["chunk"]},
                "shared": shared_param_specs()}
        if got != want:
            raise SystemExit(
                f"plan partition rules drifted from "
                f"models.llama_3d specs:\n got {got}\nwant {want}")
        print("plan verified: partition rules reproduce "
              "models.llama_3d specs", flush=True)
    rng = np.random.default_rng(0)
    shape = (cfg.num_microbatches, args.seq,
             cfg.microbatch_size * cfg.dp * cfg.ep)
    print(f"mesh dp={cfg.dp} pp={cfg.pp} tp={cfg.tp} ep={cfg.ep} "
          f"cp={cfg.cp} "
          f"chunks={cfg.num_chunks} moe={cfg.moe} ({n} devices), "
          f"{args.layers}L x {args.hidden}h", flush=True)
    t0 = time.time()
    for i in range(args.steps):
        tokens = jnp.asarray(rng.integers(0, args.vocab, shape), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        state, loss = step(state, tokens, labels)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}", flush=True)
    jax.block_until_ready(state)
    print(f"done in {time.time() - t0:.1f}s "
          f"(step counter = {int(state['step'])})")


if __name__ == "__main__":
    main()
