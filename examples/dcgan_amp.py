"""DCGAN mixed-precision training — reference ``examples/dcgan/main_amp.py``
(the second canonical amp flow: TWO models and TWO optimizers sharing the
amp machinery, ``num_losses=3`` there — errD_real/errD_fake/errG).

TPU-native shape of the same thing: one `Amp` per network (generator and
discriminator each carry their own fp32 masters + loss-scale state, as the
reference allocates one loss-scaler per loss), NHWC conv stacks (TPU conv
layout), synthetic data. The literal-parity alternative — ONE ``Amp`` with
``num_losses=3`` and ``make_train_step(loss_fn, loss_id=i)`` per loss —
is also supported (see ``docs/amp.md``); separate Amps per network are the
cleaner functional design when the two nets have disjoint params.

``python examples/dcgan_amp.py [--opt-level O2] [--steps N]``
"""

import argparse
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu.amp import Amp
from apex1_tpu.core.policy import get_policy
from apex1_tpu.optim.fused_adam import fused_adam


class Generator(nn.Module):
    """z (B, 1, 1, Z) -> image (B, 32, 32, C); ConvTranspose/BN/ReLU stack
    (BN stays fp32 under keep_norms_fp32 — amp keep_batchnorm_fp32)."""

    features: int = 64
    channels: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, z, train=True):
        f, dt = self.features, self.dtype
        x = z.astype(dt)
        for i, (feat, stride) in enumerate(
                [(f * 4, 4), (f * 2, 2), (f, 2)]):
            x = nn.ConvTranspose(feat, (4, 4), (stride, stride),
                                 padding="SAME" if i else "VALID",
                                 use_bias=False, dtype=dt)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             dtype=jnp.float32)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(self.channels, (4, 4), (2, 2), padding="SAME",
                             use_bias=False, dtype=dt)(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    """image -> logit; strided Conv/LeakyReLU stack."""

    features: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        f, dt = self.features, self.dtype
        x = x.astype(dt)
        for i, feat in enumerate([f, f * 2, f * 4]):
            x = nn.Conv(feat, (4, 4), (2, 2), padding="SAME",
                        use_bias=False, dtype=dt)(x)
            if i:
                x = nn.BatchNorm(use_running_average=not train,
                                 dtype=jnp.float32)(x)
            x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False,
                    dtype=dt)(x)
        return x.reshape(x.shape[0])


def bce_logits(logits, target):
    """binary CE with logits, fp32 (≙ reference BCELoss on fp32 sigmoid)."""
    logits = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--zdim", type=int, default=100)
    ap.add_argument("--opt-level", default="O2")
    args = ap.parse_args()

    policy = get_policy(args.opt_level)
    gen = Generator(dtype=policy.compute_dtype)
    disc = Discriminator(dtype=policy.compute_dtype)
    rng = np.random.default_rng(0)
    key = jax.random.key(0)

    z0 = jnp.zeros((args.batch, 1, 1, args.zdim), jnp.float32)
    img0 = jnp.zeros((args.batch, 32, 32, 3), jnp.float32)
    # distinct init keys: the same key for both nets would correlate
    # G's and D's initial weights (graftlint APX103 caught this)
    key_g, key_d = jax.random.split(key)
    gvars = jax.jit(gen.init)(key_g, z0)
    dvars = jax.jit(disc.init)(key_d, img0)

    # one Amp per (model, optimizer) pair — ≙ amp.initialize([netD, netG],
    # [optD, optG], num_losses=3); each keeps its own loss-scale state
    amp_g = Amp(tx=fused_adam(2e-4, b1=0.5, b2=0.999),
                opt_level=args.opt_level)
    amp_d = Amp(tx=fused_adam(2e-4, b1=0.5, b2=0.999),
                opt_level=args.opt_level)
    gstate = amp_g.init(gvars["params"])
    dstate = amp_d.init(dvars["params"])
    g_bn = gvars.get("batch_stats", {})
    d_bn = dvars.get("batch_stats", {})

    def d_loss_fn(d_params, batch):
        """errD = BCE(D(real), 1) + BCE(D(G(z)), 0) — two of the
        reference's three scaled losses."""
        real, fake, d_bn = batch
        logits_r, upd = disc.apply(
            {"params": d_params, "batch_stats": d_bn}, real,
            mutable=["batch_stats"])
        logits_f, upd = disc.apply(
            {"params": d_params, "batch_stats": upd["batch_stats"]}, fake,
            mutable=["batch_stats"])
        loss = bce_logits(logits_r, 1.0) + bce_logits(logits_f, 0.0)
        return loss, upd["batch_stats"]

    def g_loss_fn(g_params, batch):
        """errG = BCE(D(G(z)), 1)."""
        z, g_bn, d_params, d_bn = batch
        fake, upd = gen.apply(
            {"params": g_params, "batch_stats": g_bn}, z,
            mutable=["batch_stats"])
        logits = disc.apply(
            {"params": d_params, "batch_stats": d_bn}, fake, train=False)
        return bce_logits(logits, 1.0), upd["batch_stats"]

    d_step = jax.jit(amp_d.make_train_step(d_loss_fn, has_aux=True),
                     donate_argnums=0)
    g_step = jax.jit(amp_g.make_train_step(g_loss_fn, has_aux=True),
                     donate_argnums=0)

    @jax.jit
    def make_fake(g_params, g_bn, z):
        return gen.apply({"params": g_params, "batch_stats": g_bn}, z,
                         train=False)

    t0 = time.time()
    for i in range(args.steps):
        real = jnp.asarray(rng.normal(size=(args.batch, 32, 32, 3)),
                           jnp.float32)
        z = jnp.asarray(rng.normal(size=(args.batch, 1, 1, args.zdim)),
                        jnp.float32)
        fake = make_fake(gstate.params, g_bn, z)
        dstate, d_metrics = d_step(dstate, (real, fake, d_bn))
        d_bn = d_metrics["aux"]
        gstate, g_metrics = g_step(gstate, (z, g_bn, dstate.params, d_bn))
        g_bn = g_metrics["aux"]
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: errD={float(d_metrics['loss']):.4f} "
                  f"errG={float(g_metrics['loss']):.4f} "
                  f"scaleD={float(dstate.loss_scale.scale):.0f}")
    jax.block_until_ready(gstate.params)
    print(f"done in {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
