"""RNN-Transducer speech training — the end-to-end story behind
``apex1_tpu.contrib.transducer`` (reference
``apex/contrib/transducer``): an LSTM audio encoder (`apex1_tpu.rnn`,
the hoisted-projection scan RNNs), an LSTM prediction network, the
broadcast-add transducer joint, and the associative-scan α-recursion
RNN-T loss, trained with amp mixed precision + fused Adam on a
synthetic phoneme task (each label held for a few noisy audio frames;
the transducer must recover the label sequence). Greedy RNN-T decoding
(advance t on blank, u on emit) verifies the learned alignment.

``python examples/rnnt_speech.py [--steps 800] [--opt-level O2]``
(defaults reach exact-sequence greedy decode on held-out utterances in
~20s on CPU).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize

import flax.linen as nn  # noqa: E402

from apex1_tpu.amp import Amp  # noqa: E402
from apex1_tpu.contrib.transducer import (  # noqa: E402
    transducer_joint, transducer_loss)
from apex1_tpu.core.policy import get_policy  # noqa: E402
from apex1_tpu.optim.fused_adam import fused_adam  # noqa: E402
from apex1_tpu.rnn import LSTM  # noqa: E402

BLANK = 0


class RNNT(nn.Module):
    """Minimal transducer: encoder/predictor LSTMs + joint + vocab head."""

    vocab: int          # incl. blank at index 0
    feat: int
    hidden: int = 64

    @nn.compact
    def __call__(self, audio, dec_in):
        """audio (B, T, feat); dec_in (B, U) label ids with leading
        BLANK (the RNN-T prediction network's <s>). Returns
        (B, T, U, vocab) joint logits."""
        dtype = audio.dtype
        enc, _ = LSTM(self.feat, self.hidden, name="encoder")(
            audio.transpose(1, 0, 2))
        emb = self.param("embed", nn.initializers.normal(0.02),
                         (self.vocab, self.hidden), jnp.float32)
        pred, _ = LSTM(self.hidden, self.hidden, name="predictor")(
            emb[dec_in].astype(dtype).transpose(1, 0, 2))
        h = transducer_joint(enc.transpose(1, 0, 2),
                             pred.transpose(1, 0, 2), relu=True)
        w = self.param("head", nn.initializers.normal(0.02),
                       (self.hidden, self.vocab), jnp.float32)
        return h @ w.astype(h.dtype)


def make_batch(rng, batch, U_lab, frames_per, vocab, feat, proj):
    """Each utterance: U_lab labels from [1, vocab), each held for
    ``frames_per`` audio frames; audio = one-hot @ random projection +
    noise."""
    labels = rng.integers(1, vocab, (batch, U_lab))
    frames = np.repeat(labels, frames_per, axis=1)           # (B, T)
    onehot = np.eye(vocab)[frames]                           # (B, T, V)
    audio = onehot @ proj + rng.normal(0, 0.1, (batch, U_lab * frames_per,
                                                feat))
    dec_in = np.concatenate([np.zeros((batch, 1), np.int64), labels], 1)
    return (jnp.asarray(audio, jnp.float32),
            jnp.asarray(labels, jnp.int32),
            jnp.asarray(dec_in, jnp.int32))


def greedy_decode(model, params, audio, max_symbols=8):
    """Standard RNN-T greedy: at each t emit while argmax != blank
    (bounded), else advance t. Host-loop reference decoder (clarity over
    dispatch count)."""
    B, T, _ = audio.shape
    hyps = []
    for b in range(B):
        y = [BLANK]
        for t in range(T):
            for _ in range(max_symbols):
                logits = model.apply(
                    {"params": params}, audio[b:b + 1],
                    jnp.asarray([y], jnp.int32))
                k = int(jnp.argmax(logits[0, t, len(y) - 1]))
                if k == BLANK:
                    break
                y.append(k)
        hyps.append(y[1:])
    return hyps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--labels", type=int, default=5)
    ap.add_argument("--frames-per", type=int, default=3)
    ap.add_argument("--vocab", type=int, default=8)
    ap.add_argument("--feat", type=int, default=16)
    ap.add_argument("--opt-level", default="O2")
    args = ap.parse_args()

    model = RNNT(vocab=args.vocab, feat=args.feat)
    rng = np.random.default_rng(0)
    proj = rng.normal(0, 1.0, (args.vocab, args.feat))
    audio, labels, dec_in = make_batch(rng, args.batch, args.labels,
                                       args.frames_per, args.vocab,
                                       args.feat, proj)
    params = model.init(jax.random.key(0), audio, dec_in)["params"]
    n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(f"RNN-T: {n/1e3:.0f}k params, opt {args.opt_level}")

    T = args.labels * args.frames_per
    f_len = jnp.full((args.batch,), T, jnp.int32)
    y_len = jnp.full((args.batch,), args.labels, jnp.int32)

    def loss_fn(params, audio, labels, dec_in):
        logits = model.apply({"params": params}, audio, dec_in)
        return transducer_loss(logits, labels, f_len, y_len,
                               blank_idx=BLANK)

    amp = Amp(tx=fused_adam(2e-3), opt_level=args.opt_level)
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(loss_fn))
    t0 = time.time()
    for i in range(args.steps):
        audio, labels, dec_in = make_batch(rng, args.batch, args.labels,
                                           args.frames_per, args.vocab,
                                           args.feat, proj)
        state, m = step(state, audio, labels, dec_in)
        if i % 100 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  nll {float(m['loss']):.4f}")
    print(f"trained {args.steps} steps in {time.time()-t0:.1f}s")

    audio, labels, _ = make_batch(rng, 4, args.labels, args.frames_per,
                                  args.vocab, args.feat, proj)
    # an UNtrained model emits junk at up to max_symbols per frame —
    # every new hypothesis length is a fresh XLA compile in the host
    # decode loop, so short smoke runs cap the emission budget hard
    hyps = greedy_decode(model, state.params, audio,
                         max_symbols=8 if args.steps >= 100 else 2)
    want = [r.tolist() for r in np.asarray(labels)]
    exact = sum(h == w for h, w in zip(hyps, want))
    print(f"greedy exact-sequence match: {exact}/4")
    for h, w in zip(hyps[:2], want[:2]):
        print(f"  ref {w}\n  hyp {h}")


if __name__ == "__main__":
    main()
