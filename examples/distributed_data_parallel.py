"""Minimal DDP — reference ``examples/simple/distributed/
distributed_data_parallel.py`` (the 30-line apex-DDP hello world).

The reference: init NCCL process group, wrap a Linear in apex DDP, step.
TPU-native: the dp mesh axis IS the process group; one shard_map with
``grad_psum_axes=("dp",)`` is the whole of DDP.

``python examples/distributed_data_parallel.py`` (uses every visible
device; on CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu.amp import Amp
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.optim.fused_sgd import fused_sgd


def main():
    mesh = make_mesh(dp=jax.device_count())
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    Y = jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)
    params = {"w": jnp.zeros((16, 4), jnp.float32)}

    amp = Amp(tx=fused_sgd(0.1), opt_level="O0", grad_psum_axes=("dp",))
    state = amp.init(params)

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] - y))

    step = jax.jit(jax.shard_map(
        amp.make_train_step(loss_fn), mesh=mesh,
        in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
        check_vma=False))

    for i in range(10):
        state, metrics = step(state, X, Y)
        print(f"step {i} loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
