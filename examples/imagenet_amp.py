"""ResNet-50 "ImageNet" training — reference
``examples/imagenet/main_amp.py`` (amp O1/O2 + apex DDP + SyncBN +
prefetching loader), the canonical end-to-end flow (BASELINE config 3).

TPU-native shape of the same flow:
- amp opt-level      → `apex1_tpu.amp.Amp(tx, opt_level=...)`
- apex DDP allreduce → ``shard_map`` over the dp mesh axis +
                       ``grad_psum_axes=("dp",)`` (one fused psum)
- convert_syncbn     → model built with ``bn_axis_name="dp"``
- data_prefetcher    → `apex1_tpu.runtime.PrefetchLoader` with the native
                       u8→f32 normalize
Synthetic data (no dataset in the image); run with
``python examples/imagenet_amp.py [--steps N] [--opt-level O2]``.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu import runtime
from apex1_tpu.amp import Amp
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.resnet import ResNet, ResNetConfig
from apex1_tpu.ops import softmax_cross_entropy_loss
from apex1_tpu.optim.fused_sgd import fused_sgd
from apex1_tpu.utils.observability import MetricsLogger


def synthetic_loader(batch, image, steps, rng):
    for _ in range(steps):
        yield {
            "images": rng.integers(0, 256, (batch, image, image, 3),
                                   dtype=np.uint8),
            "labels": rng.integers(0, 1000, (batch,), dtype=np.int64),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--image", type=int, default=64)
    ap.add_argument("--opt-level", default="O2")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny model for smoke runs")
    args = ap.parse_args()

    n_dev = jax.device_count()
    mesh = make_mesh(dp=n_dev)
    policy = get_policy(args.opt_level)
    cfg = (ResNetConfig.tiny(bn_axis_name="dp", policy=policy)
           if args.tiny else
           ResNetConfig.resnet50(bn_axis_name="dp", policy=policy))
    model = ResNet(cfg)

    rng = np.random.default_rng(0)
    init_img = jnp.zeros((2, args.image, args.image, 3), jnp.float32)
    variables = jax.jit(model.init)(jax.random.key(0), init_img)
    amp = Amp(tx=fused_sgd(0.1, momentum=0.9), opt_level=args.opt_level,
              grad_psum_axes=("dp",))
    state = amp.init(variables["params"])
    bn_stats = variables["batch_stats"]

    def loss_fn(params, batch, bn_stats):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": bn_stats},
            batch["images"], mutable=["batch_stats"])
        loss = jnp.mean(softmax_cross_entropy_loss(
            logits, batch["labels"], smoothing=0.1))
        # thread the updated running stats out through the aux channel
        return loss, mutated["batch_stats"]

    step = jax.jit(jax.shard_map(
        amp.make_train_step(loss_fn, has_aux=True), mesh=mesh,
        in_specs=(P(), {"images": P("dp"), "labels": P("dp")}, P()),
        out_specs=(P(), P()), check_vma=False))

    mean = (0.485, 0.456, 0.406)
    std = (0.229, 0.224, 0.225)
    loader = runtime.PrefetchLoader(
        synthetic_loader(args.batch * n_dev, args.image, args.steps, rng),
        transform=lambda b: {
            "images": runtime.normalize_images(b["images"], mean, std),
            "labels": b["labels"].astype(np.int32)})
    logger = MetricsLogger()
    t0 = time.time()
    for i, batch in enumerate(loader):
        state, metrics = step(state, batch, bn_stats)
        bn_stats = metrics.pop("aux")  # SyncBN running stats advance
        if i % 5 == 0 or i == args.steps - 1:
            logger.log(i, metrics, tokens=args.batch * n_dev)
    jax.block_until_ready(state.params)
    print(f"done: {args.steps} steps, "
          f"{args.steps * args.batch * n_dev / (time.time() - t0):.0f} "
          f"imgs/sec")


if __name__ == "__main__":
    main()
