"""Distributed Llama training — BASELINE config 4's recipe end to end:
tensor parallelism via GSPMD param specs, optional ZeRO (fsdp) sharding
of params + optimizer state, data parallelism, amp-O2 mixed precision,
fused Adam. (The context-parallel forms — ring / Ulysses over a cp axis
— are shard_map programs; see `tests/test_ring_attention.py` and
`__graft_entry__.dryrun_multichip` for those flows.)

Runs on any device set — demonstrate on CPU with
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_distributed.py --tp 2 --fsdp 2 --dp 2
or on a TPU slice with the same flags spelled by the topology.

The whole distributed story is specs + one jit: no process groups, no
wrappers, no collectives in user code (SURVEY.md §7.0).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


from apex1_tpu.amp import Amp
from apex1_tpu.core.mesh import make_mesh
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.llama import (Llama, LlamaConfig, llama_loss_fn,
                                    param_specs)
from apex1_tpu.optim.fused_adam import fused_adam
from apex1_tpu.parallel import fsdp_param_specs, shard_opt_state_specs
from apex1_tpu.utils.observability import MetricsLogger


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--opt-level", default="O2")
    args = ap.parse_args()

    mesh = make_mesh(dp=args.dp, fsdp=args.fsdp, tp=args.tp)
    cfg = LlamaConfig.tiny(policy=get_policy(args.opt_level),
                           max_seq_len=args.seq)
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.seq)), jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]

    amp = Amp(tx=fused_adam(3e-4, weight_decay=0.1),
              opt_level=args.opt_level, max_grad_norm=1.0)
    state = amp.init(params)

    # TP from the model's regex rules; ZeRO by ALSO sharding any still-
    # replicated large params (and the optimizer moments, same dims)
    # over fsdp. GSPMD inserts every collective.
    tp_specs = param_specs(state.params)
    if args.fsdp > 1:
        zero = fsdp_param_specs(state.params, divisor=args.fsdp)
        tp_specs = jax.tree_util.tree_map(
            lambda t, z: z if t == P() else t, tp_specs, zero,
            is_leaf=lambda v: isinstance(v, P))
    opt_specs = shard_opt_state_specs(state.opt_state,
                                      param_specs=tp_specs)

    def put(tree, specs):
        return jax.device_put(tree, jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda v: isinstance(v, P)))

    import dataclasses
    state = dataclasses.replace(
        state,
        params=put(state.params, tp_specs),
        opt_state=put(state.opt_state, opt_specs))
    batch_spec = NamedSharding(mesh, P(("dp", "fsdp")))

    step = jax.jit(amp.make_train_step(llama_loss_fn(model)),
                   donate_argnums=0)
    logger = MetricsLogger()
    t0 = time.time()
    for i in range(args.steps):
        batch = jax.device_put(jnp.asarray(
            rng.integers(0, cfg.vocab_size, (args.batch, args.seq)),
            jnp.int32), batch_spec)
        state, metrics = step(state, batch)
        if i % 2 == 0 or i == args.steps - 1:
            logger.log(i, metrics, tokens=args.batch * args.seq)
    jax.block_until_ready(state.params)
    print(f"done in {time.time() - t0:.1f}s on mesh "
          f"{dict(mesh.shape)} — every collective GSPMD-inserted")


if __name__ == "__main__":
    main()
