"""T5 seq2seq fine-tune + generate — the encoder-decoder family the
reference's variable-shape pipeline machinery (``decoder_seq_length``)
serves, end to end: amp mixed precision + fused Adam training on a
synthetic SORTING task (the decoder must emit the encoder's tokens in
ascending order — position-free, so it suits T5's relative-position
attention), then KV-cached greedy generation
(`models.generate.t5_generate`) to verify the model actually learned
the mapping (expect ~60-80% strict token accuracy after the default
schedule; duplicate counting is the genuinely hard residue of the
task).

``python examples/t5_seq2seq.py [--opt-level O2] [--steps 1500]``
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize

from apex1_tpu.amp import Amp  # noqa: E402
from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import t5_generate
from apex1_tpu.models.t5 import T5, T5Config, t5_loss_fn
from apex1_tpu.optim.fused_adam import fused_adam


def make_batch(rng, batch, seq, vocab, pad_id=0, bos_id=1):
    """Sort task: encoder sees [2, vocab) tokens; the decoder target is
    the ascending sort wrapped as [BOS, sorted..., PAD]."""
    src = rng.integers(2, vocab, (batch, seq))
    dec = np.concatenate(
        [np.full((batch, 1), bos_id), np.sort(src, axis=1),
         np.full((batch, 1), pad_id)], axis=1)
    return jnp.asarray(src, jnp.int32), jnp.asarray(dec, jnp.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1500)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=8)
    ap.add_argument("--opt-level", default="O2")
    args = ap.parse_args()

    cfg = T5Config.tiny(vocab_size=32, d_model=128, num_heads=4,
                        head_dim=32, d_ff=256, num_encoder_layers=2,
                        num_decoder_layers=2,
                        policy=get_policy(args.opt_level))
    model = T5(cfg)
    rng = np.random.default_rng(0)
    src, dec = make_batch(rng, args.batch, args.seq, cfg.vocab_size)
    params = model.init(jax.random.key(0), src, dec)["params"]
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(f"T5 tiny: {n_params/1e6:.2f}M params, opt {args.opt_level}")

    amp = Amp(tx=fused_adam(1e-3, weight_decay=0.01),
              opt_level=args.opt_level)
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(
        t5_loss_fn(model, label_pad_id=0)))

    t0 = time.time()
    for i in range(args.steps):
        src, dec = make_batch(rng, args.batch, args.seq, cfg.vocab_size)
        state, metrics = step(state, src, dec)
        if i % 50 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(metrics['loss']):.4f}  "
                  f"scale {float(metrics['loss_scale']):.0f}")
    print(f"trained {args.steps} steps in {time.time()-t0:.1f}s")

    # greedy generation: the decoder should sort a held-out batch
    src, _ = make_batch(rng, 8, args.seq, cfg.vocab_size)
    out = t5_generate(model, state.params, src,
                      max_new_tokens=args.seq, dec_start_id=1)
    want = np.sort(np.asarray(src), axis=1)
    got = np.asarray(out)
    acc = float((got == want).mean())
    print(f"greedy decode sort accuracy: {acc:.1%}")
    for i in range(2):
        print(f"  src {np.asarray(src)[i].tolist()}")
        print(f"  out {got[i].tolist()}")


if __name__ == "__main__":
    main()
