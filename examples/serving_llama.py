"""Llama serving walkthrough — the inference half the reference never had
(apex accelerates training only; a complete framework serves the model it
just fine-tuned). Demonstrates, on one model, the whole decode stack:

1. greedy KV-cached generation (`models.generate`, one-dispatch scan);
2. RAGGED batching — mixed-length prompts served together via
   ``prompt_lens`` (left-aligned once; each row decodes exactly as if it
   were alone);
3. beam search with the GNMT length penalty;
4. int8 weight-only decode (`models.quant_decode`) — the same generate
   loop over per-out-channel int8 weights dequantized inside the Pallas
   GEMM's VMEM tiles (half the HBM weight traffic, the decode
   bottleneck);
5. speculative decoding — a small draft proposes, the target verifies a
   whole chunk per forward; output token-identical to the target's own
   greedy decode, with the per-row verify-round counts printed (the
   speedup observable);
6. prefix caching — a shared system prompt prefilled once, two user
   turns continued off it (`cache_start`), each token-exact vs the flat
   prompt.

``python examples/serving_llama.py [--tiny] [--batch 2] [--prompt-len 8]
                                   [--new 16] [--beams 4]``
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.testing import honor_jax_platforms_env

honor_jax_platforms_env()   # JAX_PLATFORMS=cpu must beat sitecustomize


import dataclasses

from apex1_tpu.core.policy import get_policy
from apex1_tpu.models.generate import (beam_search, generate,
                                       llama_decoder,
                                       speculative_generate)
from apex1_tpu.models.llama import Llama, LlamaConfig
from apex1_tpu.models.quant_decode import llama_quant_decoder


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--new", type=int, default=16)
    ap.add_argument("--beams", type=int, default=4)
    args = ap.parse_args()

    on_accel = jax.default_backend() not in ("cpu",)
    if args.tiny or not on_accel:
        cfg = LlamaConfig.tiny(policy=get_policy("O2"), max_seq_len=128)
    else:
        cfg = LlamaConfig(vocab_size=32000, max_seq_len=2048,
                          num_layers=16, num_heads=32, num_kv_heads=4,
                          hidden_size=2048, ffn_size=5632,
                          policy=get_policy("O2"))
    model = Llama(cfg)
    B, S0, N = args.batch, args.prompt_len, args.new
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, S0)),
                         jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), prompt)["params"]
    apply_fn, make_cache = llama_decoder(model)

    def timed(tag, fn):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn())
        print(f"  {tag:34s} {time.perf_counter() - t0:7.2f}s "
              f"(incl. compile)")
        return out

    print(f"== serving {B}x{S0}+{N} on {jax.default_backend()} ==")
    toks = timed("greedy generate", lambda: generate(
        apply_fn, params, prompt, max_new_tokens=N,
        cache=make_cache(B, S0 + N), vocab_size=cfg.vocab_size))
    print(f"    row0: {np.asarray(toks[0])[:10]}...")

    # ragged: same rows at their true (mixed) lengths in ONE batch
    lens = jnp.asarray([S0] + [max(1, S0 // 2)] * (B - 1), jnp.int32)
    ragged = timed("ragged generate (mixed lens)", lambda: generate(
        apply_fn, params, prompt, max_new_tokens=N,
        cache=make_cache(B, S0 + N), vocab_size=cfg.vocab_size,
        prompt_lens=lens))
    print(f"    lens {np.asarray(lens)} -> row1: "
          f"{np.asarray(ragged[1])[:10]}...")

    beams, scores = timed(f"beam search K={args.beams}, lp=1.0",
                          lambda: beam_search(
        apply_fn, params, prompt, max_new_tokens=N,
        cache=make_cache(B * args.beams, S0 + N),
        num_beams=args.beams, length_penalty=1.0,
        vocab_size=cfg.vocab_size))
    print(f"    best scores: {np.asarray(scores).round(3)}")

    apply_q, make_cache_q, qparams = llama_quant_decoder(model, params)
    toks_q = timed("int8 weight-only generate", lambda: generate(
        apply_q, qparams, prompt, max_new_tokens=N,
        cache=make_cache_q(B, S0 + N), vocab_size=cfg.vocab_size))
    agree = float((np.asarray(toks_q) == np.asarray(toks)).mean())
    print(f"    token agreement with bf16: {agree:.2f} "
          f"(quantization shifts logits; ~1.0 expected at these sizes)")

    # speculative: a shallow draft of the same family; identical tokens,
    # fewer target forwards when the draft agrees
    draft_cfg = dataclasses.replace(
        cfg, num_layers=max(1, cfg.num_layers // 4))
    draft = Llama(draft_cfg)
    pd = jax.jit(draft.init)(jax.random.key(7), prompt)["params"]
    d_fn, make_cache_d = llama_decoder(draft)
    K = 4
    toks_s, rounds = timed("speculative (K=4, shallow draft)",
                           lambda: speculative_generate(
        apply_fn, params, d_fn, pd, prompt, max_new_tokens=N,
        target_cache=make_cache(B, S0 + N + K + 1),
        draft_cache=make_cache_d(B, S0 + N + K + 1),
        num_draft=K, vocab_size=cfg.vocab_size))
    assert (np.asarray(toks_s) == np.asarray(toks)).all(), \
        "speculative output must be token-identical to greedy"
    print(f"    verify rounds/row {np.asarray(rounds).tolist()} vs "
          f"{N - 1} greedy target forwards (untrained draft -> little "
          f"agreement; a distilled draft shrinks rounds toward "
          f"{(N - 1 + K) // (K + 1)})")

    # prefix caching: prefill the "system prompt" once, continue turns
    Ls = max(2, S0 // 2)
    cache_pre = make_cache(B, S0 + Ls + N)
    _, cache_pre = jax.jit(apply_fn)(params, prompt, cache_pre, 0)
    agrees = []
    for turn in range(2):
        user = jnp.asarray(
            np.random.default_rng(100 + turn).integers(
                1, cfg.vocab_size, (B, Ls)), jnp.int32)
        cont = timed(f"prefix-cached turn {turn}", lambda: generate(
            apply_fn, params, user, max_new_tokens=N, cache=cache_pre,
            cache_start=S0, vocab_size=cfg.vocab_size))
        flat = generate(apply_fn, params,
                        jnp.concatenate([prompt, user], 1),
                        max_new_tokens=N, cache=make_cache(B, S0 + Ls + N),
                        vocab_size=cfg.vocab_size)
        agrees.append(float(
            (np.asarray(cont) == np.asarray(flat)).mean()))
    # this walkthrough runs the O2 (bf16) policy: the chunk-decode
    # continuation prefill and the flat flash prefill round differently
    # in bf16, so a near-tie argmax can flip — exactness holds at fp32
    # (pinned in test_generate::TestPrefixCaching); report agreement
    # like the int8 section rather than asserting it
    print(f"    2 turns off one cached prefix; token agreement vs flat "
          f"{[round(a, 2) for a in agrees]} (exact under fp32; bf16 "
          f"rounds near-ties differently across the two prefill paths)")
    print("serving walkthrough done")


if __name__ == "__main__":
    main()
